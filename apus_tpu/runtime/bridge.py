"""Bridge: the replica daemon's half of the native proxy protocol.

The reference proxy and consensus share one address space: captured
requests flow through a spinlocked tailq (message.h:5-23) and commit
release is two shared counters (cur_rec/highest_rec, proxy.c:45-46,
proxy.c:160).  Our consensus runs in a separate daemon process, so this
module terminates the proxy's unix-socket record stream, submits each
record into the protocol ``Node``, and releases the app's spinning
thread by writing ``highest_rec`` into the shared-memory block the proxy
mmaps (native/apus_wire.h is the authoritative layout).

Replay (the reference's follower half, do_action_connect/send/close,
proxy.c:373-439) also lives here: committed records captured by *other*
replicas are replayed into the local unmodified app over loopback TCP.
A dedicated replay thread does the socket I/O so the protocol tick
thread never blocks on the app.
"""

from __future__ import annotations

import collections
import mmap
import os
import queue
import select
import socket
import struct
import threading
import time
from typing import Optional

from apus_tpu.core.log import LogEntry
from apus_tpu.core.types import EntryType, ProxyAction
from apus_tpu.models.sm import Snapshot, StateMachine

# -- shm layout (native/apus_wire.h parity) -------------------------------
SHM_MAGIC = b"APUSSHM2"
SHM_SIZE = 88
_OFF_HIGHEST = 8
_OFF_IS_LEADER = 16
_OFF_TERM = 24
_OFF_CUR_REC = 32
_OFF_ABORTED = 40
_OFF_SPIN_TIMEOUTS = 48
_OFF_ABORT_FLOOR = 56
_OFF_FOLLOWER_READS = 64
_OFF_MISDIRECT_REFUSALS = 72
_OFF_LEADER_HINT = 80       # leader slot + 1; 0 = unknown (FindLeader)

# proxy -> daemon frame body: u8 action | u64 conn_id | u64 cur_rec | data
_HDR = struct.Struct("<BQQ")

# Replicated record payload (the opaque "command" in the log entry):
# u8 action | u64 conn_id | u64 clt_id | u64 req_id | data.  clt_id and
# req_id mirror the log entry's own fields: snapshot replay works from
# the relay SM's record dump, where entry metadata is gone, yet must
# still route records by origin (skip ones this app executed live).
_REC = struct.Struct("<BQQQ")

#: clt_id namespace for bridge-submitted records — disjoint from real
#: client ids (ApusClient masks to 63 bits) so apply-time routing can
#: recognize proxy records by the top bit.
BRIDGE_CLT_BASE = 1 << 63


def bridge_clt_id(replica_idx: int) -> int:
    return BRIDGE_CLT_BASE | replica_idx


def is_bridge_clt(clt_id: int) -> bool:
    return bool(clt_id & BRIDGE_CLT_BASE)


def encode_record(action: int, conn_id: int, data: bytes,
                  clt_id: int = 0, req_id: int = 0) -> bytes:
    return _REC.pack(action, conn_id, clt_id, req_id) + data


def decode_record(payload: bytes) -> tuple[int, int, bytes, int, int]:
    action, conn_id, clt_id, req_id = _REC.unpack_from(payload, 0)
    return action, conn_id, payload[_REC.size:], clt_id, req_id


class RelayStateMachine(StateMachine):
    """SM used by proxied replicas: the *real* state machine is the
    replayed application (as in the reference, where the built-in KVS is
    vestigial under APUS, dare_server.c:265-274).  Applied records are
    retained so snapshots can rebuild a joiner's app by re-replay — the
    reference's snapshot likewise *is* the proxy's durable record dump
    (proxy.c:300, stablestorage_dump_records), which lives in
    BerkeleyDB ON DISK (db-interface.c:21-51), not RAM.  With
    ``spill_path`` this SM keeps the dump on disk the same way
    (append-only, length-framed; a 20-minute endurance soak grew
    daemon RSS without bound before this); the in-memory list remains
    for pathless in-process clusters (tests)."""

    def __init__(self, spill_path=None) -> None:
        self.records: list[bytes] = []
        self.record_count = 0
        self.record_bytes = 0
        #: Bumped whenever the dump is REPLACED (apply_snapshot): the
        #: streaming pusher fences each chunk read on this, because the
        #: append-only/frozen-prefix invariant breaks exactly when a
        #: deposed leader's own dump gets rewritten by the new
        #: leader's snapshot push mid-stream.
        self.dump_generation = 0
        # Delta-snapshot bookkeeping: the relay dump is an append-only
        # deterministic function of the applied prefix, so the delta
        # past a rejoiner's applied determinant is simply the DUMP
        # SUFFIX appended after it.  ``_idx_offsets`` maps applied log
        # index -> dump byte offset BEFORE that record (bounded ring;
        # the oldest retained index is the delta floor).  A full
        # install anchors the floor at the snapshot point.
        self._idx_offsets: collections.deque = \
            collections.deque(maxlen=self.DELTA_TRACK_CAP)
        self.delta_floor = 0
        self._delta_anchor: tuple[int, int] = (0, 0)  # (idx, offset)
        if spill_path:
            os.makedirs(os.path.dirname(spill_path) or ".",
                        exist_ok=True)
            # wb+: recovery replays committed history back through
            # apply(), so a restart starts the dump clean.
            self._f = open(spill_path, "wb+")
        else:
            self._f = None

    #: applied-index watermarks retained for delta production (one
    #: tuple per applied record; beyond the cap the delta floor rises
    #: — older bases fall back to a full push).  Sized to the same
    #: order as the store's compaction retention (a few MB of RAM).
    DELTA_TRACK_CAP = 1 << 16

    def apply(self, idx: int, cmd: bytes) -> bytes:
        if idx:
            before = (self._f.tell() if self._f is not None
                      else self.record_bytes + 4 * self.record_count)
            self._idx_offsets.append((idx, before))
            if len(self._idx_offsets) == self._idx_offsets.maxlen:
                # Ring full: the floor is now the oldest retained base.
                self.delta_floor = max(self.delta_floor,
                                       self._idx_offsets[0][0])
        if self._f is not None:
            self._f.write(struct.pack("<I", len(cmd)) + cmd)
        else:
            self.records.append(cmd)
        self.record_count += 1
        self.record_bytes += len(cmd)
        return b"OK"

    def snapshot_stream_size(self):
        """Size of the on-disk record dump, or None when the dump is
        in-memory (streaming would buy nothing there).  Captured under
        the daemon lock at snapshot-meta creation: the spill file is
        append-only and appends happen under the same lock, so the
        prefix [0, size) is immutable afterwards — it IS the dump at
        that apply point."""
        if self._f is None:
            return None
        self._f.flush()
        return os.fstat(self._f.fileno()).st_size

    def read_snapshot_chunk(self, off: int, n: int) -> bytes:
        """pread of the frozen dump prefix (no shared seek state with
        the append path)."""
        assert self._f is not None
        return os.pread(self._f.fileno(), n, off)

    def snapshot_spool_dir(self) -> str | None:
        """Directory for assembling an INBOUND snapshot stream: the
        spill's own directory, so adoption is a same-filesystem rename
        (see onesided.apply_snap_begin)."""
        if self._f is None:
            return None
        return os.path.dirname(self._f.name) or "."

    def dup_dump_fd(self) -> int:
        """Duplicate fd of the CURRENT dump file, for a background
        snapshot stream: installs replace the file (fresh inode — see
        apply_snapshot), so this fd pins the immutable captured dump
        for the stream's lifetime regardless of concurrent installs.
        Caller closes it."""
        assert self._f is not None
        return os.dup(self._f.fileno())

    # -- delta snapshots (models.sm contract) ------------------------------

    def _dump_size(self) -> int:
        if self._f is None:
            return self.record_bytes + 4 * self.record_count
        self._f.flush()
        return os.fstat(self._f.fileno()).st_size

    def delta_since(self, base_idx: int) -> bytes | None:
        """The dump SUFFIX appended after applied index ``base_idx`` —
        the relay dump is append-only and deterministic in the applied
        prefix, so this IS the state delta a rejoiner at that
        determinant needs.  None when the base predates the tracked
        watermark window (full push instead)."""
        if base_idx < self.delta_floor:
            return None
        size = self._dump_size()
        off = size
        for idx, before in self._idx_offsets:
            if idx > base_idx:
                off = before
                break
        if off >= size:
            return b""
        if self._f is not None:
            return os.pread(self._f.fileno(), size - off, off)
        # In-memory mode: walk records backward until the suffix
        # reaches ``off`` (frames are 4-byte-length-prefixed).
        acc = 0
        take = []
        for rec in reversed(self.records):
            if size - acc <= off:
                break
            take.append(rec)
            acc += 4 + len(rec)
        return b"".join(struct.pack("<I", len(r)) + r
                        for r in reversed(take))

    def apply_snapshot_delta(self, snap: Snapshot) -> None:
        """Merge a ``delta_since`` blob: APPEND the record frames to
        the dump (no replace — the append-only invariant and any
        pinned reader fds stay intact; dump_generation unchanged) and
        advance the gauges.  Per-record indices inside the delta span
        are unknown, so delta tracking re-anchors at the snapshot
        point."""
        added = 0
        off = 0
        buf = snap.data
        recs = []
        while off < len(buf):
            (n,) = struct.unpack_from("<I", buf, off)
            off += 4
            recs.append(buf[off:off + n])
            off += n
            added += 1
        if self._f is not None:
            self._f.seek(0, os.SEEK_END)
            self._f.write(buf)
            self._f.flush()
        else:
            self.records.extend(recs)
        self.record_count += added
        self.record_bytes += sum(len(r) for r in recs)
        self._idx_offsets.clear()
        self.delta_floor = snap.last_idx
        self._delta_anchor = (snap.last_idx, self._dump_size())

    def iter_records(self) -> list[bytes]:
        """The full record dump, mode-independent — what the Bridge's
        snapshot prime, dirty-app reprime, and deep-NACK fallback
        consume (the dump_records analog, db-interface.c:98-128).  In
        spill mode this reads the file (those paths are rare and
        already O(history))."""
        if self._f is None:
            return list(self.records)
        self._f.flush()
        self._f.seek(0)
        blob = self._f.read()
        out: list[bytes] = []
        off = 0
        while off + 4 <= len(blob):
            (n,) = struct.unpack_from("<I", blob, off)
            off += 4
            out.append(blob[off:off + n])
            off += n
        return out

    def create_snapshot(self, last_idx: int, last_term: int) -> Snapshot:
        if self._f is not None:
            self._f.flush()
            self._f.seek(0)
            blob = self._f.read()
        else:
            blob = b"".join(struct.pack("<I", len(r)) + r
                            for r in self.records)
        return Snapshot(last_idx, last_term, blob)

    def apply_snapshot(self, snap: Snapshot) -> None:
        self.records = []
        self.record_count = 0
        self.record_bytes = 0
        self.dump_generation += 1
        # Full replace: per-record history before the snapshot point
        # is unknown — deltas re-anchor there.
        self._idx_offsets.clear()
        self.delta_floor = snap.last_idx
        self._delta_anchor = (snap.last_idx, 0)
        if self._f is not None:
            # Replace, NEVER truncate in place: a background snapshot
            # stream may hold a dup'd fd of the old dump (dup_dump_fd)
            # — replacing gives the file a fresh inode, so the pinned
            # fd keeps reading the immutable OLD content instead of a
            # torn mix of two histories.
            spill = self._f.name
            self._f.close()
            tmp = spill + ".tmp"
            with open(tmp, "wb") as f:
                f.write(snap.data)
            os.replace(tmp, spill)
            self._f = open(spill, "rb+")
            self._f.seek(0, os.SEEK_END)
        off = 0
        while off < len(snap.data):
            (n,) = struct.unpack_from("<I", snap.data, off)
            off += 4
            if self._f is None:
                self.records.append(snap.data[off:off + n])
            self.record_count += 1
            self.record_bytes += n
            off += n

    #: chunk size for file adoption/scan (one chunk resident, ever)
    _SNAP_IO_CHUNK = 1 << 20

    def apply_snapshot_file(self, snap: Snapshot, path: str,
                            adopt: bool = False) -> str | None:
        """Install from a disk file WITHOUT materializing the dump —
        the receiver half of the chunked snapshot stream.  The
        reference's snapshot *is* its disk-backed BDB record dump
        (proxy.c:306-339); ours is the same length-framed record dump,
        so installation is (a) make the file BE the spill
        (``adopt=True``: one rename; else a chunked copy), then (b)
        one buffered scan to rebuild the record gauges.  Peak resident
        footprint: one 1 MB chunk, for any dump size — this is the
        half the pusher-side streaming left open (the whole-blob
        ``apply_snapshot`` re-materialized O(history) on the
        receiver)."""
        if self._f is None:
            # In-memory mode (pathless test clusters): nothing to
            # adopt into; fall back to the materializing path.
            return super().apply_snapshot_file(snap, path, adopt)
        self.records = []
        self.record_count = 0
        self.record_bytes = 0
        self.dump_generation += 1
        self._idx_offsets.clear()
        self.delta_floor = snap.last_idx
        self._delta_anchor = (snap.last_idx, 0)
        spill = self._f.name
        self._f.close()
        if adopt:
            try:
                os.replace(path, spill)
            except OSError:
                # Cross-filesystem rename (EXDEV): the spool-dir hint
                # normally prevents this; fall back to the chunked copy.
                adopt = False
        if not adopt:
            # tmp + replace (fresh inode) for the same dup-fd pinning
            # reason as apply_snapshot.
            tmp = spill + ".install-tmp"
            with open(path, "rb") as src, open(tmp, "wb") as dst:
                while True:
                    chunk = src.read(self._SNAP_IO_CHUNK)
                    if not chunk:
                        break
                    dst.write(chunk)
            os.replace(tmp, spill)
        # Reopen positioned at the end: apply() appends, the pusher's
        # read_snapshot_chunk preads (no shared seek state).
        self._f = open(spill, "rb+")
        self._f.seek(0, os.SEEK_END)
        # Buffered frame scan (headers + skips, one chunk resident):
        # rebuilds record_count/record_bytes — the soak's leak gauges.
        with open(spill, "rb") as f:
            buf = b""
            off = 0
            while True:
                while len(buf) - off < 4:
                    more = f.read(self._SNAP_IO_CHUNK)
                    if not more:
                        if len(buf) - off not in (0,):
                            raise ValueError(
                                f"torn record header at tail of {spill}")
                        return spill
                    buf = buf[off:] + more
                    off = 0
                (n,) = struct.unpack_from("<I", buf, off)
                off += 4
                self.record_count += 1
                self.record_bytes += n
                # Skip the payload, buffered or beyond.
                avail = len(buf) - off
                if n <= avail:
                    off += n
                else:
                    f.seek(n - avail, os.SEEK_CUR)
                    buf = b""
                    off = 0


class Replayer:
    """Replays committed records into the local unmodified app
    (do_action_to_server analog, proxy.c:341-439).  Runs on its own
    thread; the app's replies are drained and discarded (the reference
    optionally logs them, proxy.c:354-366)."""

    #: Reconnect-and-resend attempts per record before declaring the
    #: app dirty and falling back to a full re-prime.
    MAX_RETRIES = 3

    def __init__(self, app_host: str, app_port: int, logger=None,
                 req_log_path: str | None = None):
        self.app = (app_host, app_port)
        self.logger = logger
        # Replayed-request log (the reference's req_log knob: every
        # action replayed into the local app is appended to
        # node-proxy-req.log, proxy.c:470-484, do_action_to_server
        # :344-366).  Off unless ClusterSpec.req_log is set.
        self._req_log = open(req_log_path, "a") if req_log_path else None
        self._q: "queue.Queue[Optional[tuple[int, int, bytes]]]" = \
            queue.Queue()
        self._conns: dict[int, socket.socket] = {}
        self._thread: Optional[threading.Thread] = None
        self.replayed = 0
        self.failed = 0          # records given up on after retries
        self.reprimes = 0        # full history re-primes performed
        self.dirty = False       # app state diverged; re-prime pending
        self._stopping = False
        #: _connect attempts (x100ms); tests shrink this so the
        #: app-down failure path stays fast.
        self.connect_attempts = 50
        #: Set by the bridge: returns the full (action, conn_id, data)
        #: record history to rebuild a dirty app from (the same dump a
        #: leader-pushed snapshot primes a joiner with).
        self.reprime_source = None

    def start(self) -> None:
        t = threading.Thread(target=self._run, name="apus-replay",
                             daemon=True)
        t.start()
        self._thread = t

    def stop(self) -> None:
        # Quiet shutdown: records still queued behind the sentinel are
        # best-effort — failures must not trigger retries/re-primes
        # against an app that is being torn down with us.
        self._stopping = True
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        for s in self._conns.values():
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()
        if self._req_log is not None:
            try:
                self._req_log.close()
            except OSError:
                pass

    def submit(self, action: int, conn_id: int, data: bytes) -> None:
        self._q.put((action, conn_id, data))

    # -- worker -----------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            action, conn_id, data = item
            if self.dirty:
                # A previous failure left the app diverged (re-prime
                # attempted then failed too — app still down).  Retry
                # the rebuild; the current record is already part of
                # the retained history the re-prime replays (it was
                # applied to the relay SM before this upcall), so it is
                # NEVER applied directly while dirty — landing it on an
                # un-primed app would reorder it ahead of the missing
                # prefix and freeze the divergence in.
                self._reprime()
                continue
            try:
                self._replay(action, conn_id, data)
                self.replayed += 1
            except OSError as e:
                if self._stopping:
                    continue      # teardown race, not app divergence
                # A committed record could not be applied to the local
                # app even with bounded reconnection: the app has
                # diverged from the replicated history (likely crashed
                # and restarted empty).  Dropping the record here would
                # silently serve wrong data after a failover, so
                # rebuild the app from the retained record history —
                # the same dump a leader-pushed snapshot primes a
                # joiner with (proxy.c:306-339).
                self.failed += 1
                self.dirty = True
                if self.logger is not None:
                    self.logger.error(
                        "replay %s conn=%x failed after %d attempts "
                        "(%s); re-priming app from record history",
                        ProxyAction(action).name, conn_id,
                        self.MAX_RETRIES, e)
                self._reprime()

    def _req_log_write(self, action: int, conn_id: int,
                       data: bytes) -> None:
        """Observability only: a log-file failure (disk full, closed on
        teardown) must never be confused with app divergence or kill
        the replay worker — it just disables the log."""
        if self._req_log is None:
            return
        try:
            self._req_log.write("%.6f %s conn=%x len=%d\n" % (
                time.time(), ProxyAction(action).name, conn_id, len(data)))
            self._req_log.flush()
        except Exception:                            # noqa: BLE001
            self._req_log = None

    def _replay(self, action: int, conn_id: int, data: bytes) -> None:
        self._req_log_write(action, conn_id, data)
        if action == ProxyAction.CONNECT:
            self._conns[conn_id] = self._connect()
        elif action == ProxyAction.SEND:
            last: Optional[OSError] = None
            for _ in range(self.MAX_RETRIES):
                conn = self._conns.get(conn_id)
                if conn is None:
                    # Record stream started before we did (e.g. joiner
                    # whose snapshot replay recreated state but not live
                    # sockets) — or the previous attempt tore it down.
                    conn = self._conns[conn_id] = self._connect()
                try:
                    conn.sendall(data)
                    self._drain(conn)
                    return
                except OSError as e:
                    # Broken app socket: reconnect and resend.  The
                    # record is one whole captured request span, so
                    # resending it on a fresh connection preserves the
                    # app-visible framing.
                    last = e
                    self._conns.pop(conn_id, None)
                    try:
                        conn.close()
                    except OSError:
                        pass
            raise last or OSError("replay send failed")
        elif action == ProxyAction.CLOSE:
            conn = self._conns.pop(conn_id, None)
            if conn is not None:
                conn.close()

    def _reprime(self) -> None:
        """Rebuild a dirty app by replaying the full retained record
        history.  At-least-once across the repair: records that DID land
        before the failure are applied again (strictly better than the
        silent drop this path replaces — replayed records are whole
        client requests, and the SET-shaped traffic this layer carries
        converges under re-application)."""
        if self.reprime_source is None:
            return
        try:
            records = self.reprime_source()
        except Exception:                                # noqa: BLE001
            return
        for s in self._conns.values():
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()
        self.reprimes += 1
        for action, conn_id, data in records:
            try:
                self._replay(action, conn_id, data)
            except OSError as e:
                self.failed += 1
                if self.logger is not None:
                    self.logger.error(
                        "re-prime replay failed (%s); app remains dirty "
                        "until the next committed record retries", e)
                return
        self.dirty = False
        if self.logger is not None:
            self.logger.info("re-primed app with %d records",
                             len(records))

    #: Source address replay connections bind to.  The interposer
    #: recognizes this peer address at accept time and permanently
    #: excludes the connection from capture — otherwise a follower that
    #: becomes leader mid-replay would re-capture replayed bytes and
    #: double-replicate them.  (The reference's analog is the is_inner
    #: thread check, proxy.c:91-106: replay I/O there is issued by the
    #: consensus thread inside the same process.)
    REPLAY_SRC = "127.0.0.2"

    def _connect(self) -> socket.socket:
        last: Optional[OSError] = None
        for _ in range(self.connect_attempts):   # app may still be starting
            try:
                s = socket.create_connection(
                    self.app, timeout=1.0,
                    source_address=(self.REPLAY_SRC, 0))
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # Blocking sends (with a generous timeout) — a partial
                # non-blocking send would tear the replayed byte stream.
                s.settimeout(10.0)
                return s
            except OSError as e:
                last = e
                time.sleep(0.1)
        raise last or OSError("replay connect failed")

    def _drain(self, conn: socket.socket) -> None:
        """Discard pending replies so the app's send buffer never fills.
        Readability is pre-checked with a zero-timeout select — a plain
        recv on a timeout-mode socket would block up to the send timeout
        when the app hasn't replied yet.  EOF raises: the app closed the
        connection under us, so the record just sent may never have been
        processed — the caller's bounded retry resends it on a fresh
        connection (at-least-once, vs silently feeding a dead socket)."""
        while select.select([conn], [], [], 0)[0]:
            if not conn.recv(65536):
                raise OSError("app closed replay connection")


class Bridge:
    """Daemon-side endpoint for one replica's native proxy."""

    def __init__(self, daemon, workdir: str,
                 app_host: Optional[str] = None,
                 app_port: Optional[int] = None):
        self.daemon = daemon
        self.idx = daemon.idx
        self.clt_id = bridge_clt_id(self.idx)
        self.logger = daemon.logger
        os.makedirs(workdir, exist_ok=True)
        self.shm_path = os.path.join(workdir, f"bridge{self.idx}.shm")
        self.sock_path = os.path.join(workdir, f"bridge{self.idx}.sock")

        host = app_host if app_host is not None else daemon.spec.app_host
        port = app_port if app_port is not None else daemon.spec.app_port
        req_log_path = None
        if getattr(daemon.spec, "req_log", False):
            req_log_path = os.path.join(
                workdir, f"node{self.idx}-proxy-req.log")
        self.replayer = Replayer(host, port, self.logger,
                                 req_log_path=req_log_path)
        self.replayer.reprime_source = self._reprime_records
        self._spin_timeouts_seen = 0
        # Record ranges whose reads the proxy FAILED (NACK frames):
        # committed members must be locally replayed (see _handle_nack).
        # _nack_replayed marks which already were — the NACK frame and
        # the commit upcall race in both orders, and each path replays
        # only if the other hasn't (exactly-once per record).
        self._nacked: list[tuple[int, int]] = []
        self._nack_replayed: set[tuple[int, int]] = set()

        # shm block: create + zero + magic.
        with open(self.shm_path, "wb") as f:
            f.write(SHM_MAGIC + b"\0" * (SHM_SIZE - len(SHM_MAGIC)))
        self._shm_file = open(self.shm_path, "r+b")
        self._shm = mmap.mmap(self._shm_file.fileno(), SHM_SIZE)
        # Guards every shm counter update: _release/abort accounting runs
        # from both bridge reader threads and the daemon tick thread, and
        # an unsynchronized check-then-write could move highest_rec
        # backwards (stranding a spinning app thread).
        self._shm_lock = threading.Lock()

        # Restart continuity: record numbering must stay strictly above
        # every req_id this bridge EVER issued — including pre-crash
        # records that were logged but not yet applied (the durable
        # store holds applied entries only, so their req_ids are not
        # recoverable locally; a peer may still deliver them during
        # catch-up, and a collision would make exactly-once dedup
        # swallow a fresh distinct capture).  A wall-clock-seconds boot
        # epoch in the high half makes every restart's numbering range
        # disjoint and per-client monotone, as the endpoint DB requires.
        ep = daemon.node.epdb.search(self.clt_id)
        base = max(int(time.time()) << 32,
                   (ep.last_req_id + 1) if ep is not None else 0)
        self._shm_set(_OFF_CUR_REC, base)
        self._shm_set(_OFF_HIGHEST, base)
        # Misdirection gate (apus_wire.h follower_reads): by default a
        # NON-leader's proxy REFUSES client bytes — a client attached
        # to a demoted/never-leader replica reconnects instead of
        # silently talking to unreplicated state.  Verification and
        # maintenance harnesses opt into stale follower reads via
        # spec.follower_reads or the runtime setter (wire op).
        self._shm_set(_OFF_FOLLOWER_READS,
                      1 if getattr(daemon.spec, "follower_reads", False)
                      else 0)
        daemon.follower_reads_setter = self.set_follower_reads
        daemon.misdirect_refusals =             lambda: self._shm_get(_OFF_MISDIRECT_REFUSALS)
        self._last_submitted = base
        self._boot_base = base
        # (clt_id, req_id) of every record already routed to the local
        # app this incarnation (released or replayed): snapshot replay
        # must skip these or a live replica that falls behind the pruned
        # head would re-execute its whole history.  Per-clt rids route
        # in MONOTONE order (the proxy's cur_rec fetch-add, in capture
        # order; aborted rids never commit at all), so a per-clt
        # frontier is exact — and O(#replicas) RAM instead of
        # O(history) (a 20-minute soak grew the old set without bound).
        self._routed_hi: dict[int, int] = {}
        # rid -> encoded record for OWN routed records, so _handle_nack
        # resolves a range in O(range) instead of scanning the whole
        # never-pruned relay history under the daemon lock (the values
        # alias the bytes the relay SM retains anyway — no copy).
        # Bounded window: beyond the cap, oldest entries evict and
        # ranges reaching below ``_own_routed_floor`` fall back to the
        # full scan (a NACK can only reference recent in-flight reads,
        # so the fallback is a never-in-practice safety net).
        self._own_routed: "collections.OrderedDict[int, bytes]" = \
            collections.OrderedDict()
        self._own_routed_floor = 0
        self._OWN_ROUTED_CAP = 65536

        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        self._lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lsock.bind(self.sock_path)
        self._lsock.listen(4)
        self._lsock.settimeout(0.2)

        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._sub_lock = threading.Lock()

        daemon.on_commit.append(self._on_commit)
        # Role/term mirrored into shm inside the daemon tick (under the
        # node lock): a client that observed leadership via the locked
        # wait_for_leader path is then guaranteed an open capture gate.
        daemon.on_tick.append(self._mirror_role)
        # A leader-pushed snapshot replaced the relay SM wholesale: the
        # local app (freshly started for a joiner) must be primed by
        # replaying every snapshot-covered record (the reference's
        # proxy_apply_db_snapshot replays its dump the same way,
        # proxy.c:306-339).
        daemon.on_snapshot.append(self._on_snapshot)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.replayer.start()
        t = threading.Thread(target=self._accept_loop,
                             name=f"apus-bridge-accept-{self.idx}",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        # Unhook from the daemon first (under its lock) so no tick can
        # touch the mmap once it's closed below.
        with self.daemon.lock:
            if self._mirror_role in self.daemon.on_tick:
                self.daemon.on_tick.remove(self._mirror_role)
            if self._on_commit in self.daemon.on_commit:
                self.daemon.on_commit.remove(self._on_commit)
            if self._on_snapshot in self.daemon.on_snapshot:
                self.daemon.on_snapshot.remove(self._on_snapshot)
            # Symmetric with the hooks above: a late OP_STATUS /
            # OP_MAINT_READS must not dereference the closed mmap.
            if getattr(self.daemon, "follower_reads_setter", None) \
                    is self.set_follower_reads:
                self.daemon.follower_reads_setter = None
                self.daemon.misdirect_refusals = None
        for t in self._threads:
            t.join(timeout=2.0)
        self.replayer.stop()
        self._lsock.close()
        self._shm.close()
        self._shm_file.close()
        for p in (self.sock_path,):
            try:
                os.unlink(p)
            except OSError:
                pass

    def set_follower_reads(self, allow: bool) -> None:
        """Runtime maintenance switch (wire op OP_MAINT_READS): allow or
        refuse stale client reads on this replica's raw app while it is
        not the leader."""
        self._shm_set(_OFF_FOLLOWER_READS, 1 if allow else 0)

    # -- shm accessors ----------------------------------------------------

    def _shm_get(self, off: int) -> int:
        return struct.unpack_from("<Q", self._shm, off)[0]

    def _shm_set(self, off: int, val: int) -> None:
        struct.pack_into("<Q", self._shm, off, val)

    @property
    def highest_rec(self) -> int:
        return self._shm_get(_OFF_HIGHEST)

    def _release(self, rec: int, abort: bool = False) -> None:
        """Monotone advance of the release channels
        (update_highest_rec analog, proxy.c:263-267) — SPLIT by
        verdict: commit releases raise ``highest_rec``, abort sweeps
        raise ``abort_floor``.  The proxy's spin exits when either
        covers its record and fails the app's read iff the floor does
        (then NACKs, so records that commit anyway get locally
        replayed) — no byte the app acts on ever escapes replication,
        and no client gets an ack for an unreplicated write."""
        with self._shm_lock:
            if abort:
                prev = max(self._shm_get(_OFF_HIGHEST),
                           self._shm_get(_OFF_ABORT_FLOOR))
                if rec > self._shm_get(_OFF_ABORT_FLOOR):
                    self._shm_set(_OFF_ABORT_FLOOR, rec)
                if rec > prev:
                    self._shm_set(_OFF_ABORTED,
                                  self._shm_get(_OFF_ABORTED) + rec - prev)
            elif rec > self._shm_get(_OFF_HIGHEST):
                self._shm_set(_OFF_HIGHEST, rec)

    # -- proxy socket -----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._reader, args=(conn,),
                                 name=f"apus-bridge-rd-{self.idx}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _reader(self, conn: socket.socket) -> None:
        """Drain one proxy connection: frames arrive in cur_rec order
        (the tailq-drain analog, get_tailq_message dare_ibv_ud.c:780-790)."""
        conn.settimeout(0.5)
        buf = b""
        try:
            while not self._stop.is_set():
                try:
                    chunk = conn.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                buf = self._consume(buf)
        finally:
            conn.close()

    def _consume(self, buf: bytes) -> bytes:
        off = 0
        while len(buf) - off >= 4:
            (n,) = struct.unpack_from("<I", buf, off)
            if len(buf) - off - 4 < n:
                break
            body = buf[off + 4:off + 4 + n]
            off += 4 + n
            action, conn_id, cur_rec = _HDR.unpack_from(body, 0)
            if action == ProxyAction.NACK:
                self._handle_nack(conn_id, cur_rec)
            else:
                self._submit(action, conn_id, cur_rec, body[_HDR.size:])
        return buf[off:]

    def _handle_nack(self, lo: int, hi: int) -> None:
        """The proxy failed the app's read covering records [lo, hi] —
        the app executed none of their bytes.  Any of them that COMMIT
        (the abort sweep raced a commit the new leader preserved) must
        be replayed into our own app like a foreign record, or this
        app alone would miss a write every other replica applies.
        Already-committed members replay now — marked in the routed frontier
        under the daemon lock so a racing ``_on_commit`` upcall can't
        replay them a second time; future ones at their _on_commit (the
        range is remembered)."""
        to_replay = []
        with self.daemon.lock:
            self._nacked.append((lo, hi))
            # Replay only records whose commit upcall ALREADY ran
            # (rid in _own_routed implies the frontier passed it — it
            # saw no NACK then); ones still in the upcall queue see the
            # range at _on_commit.  O(range) via the rid index; ranges
            # reaching below the index window scan the full history.
            if lo > self._own_routed_floor:
                candidates = [(rid, self._own_routed[rid])
                              for rid in range(lo, hi + 1)
                              if rid in self._own_routed]
            else:
                candidates = []
                for rec in self._sm_records():
                    try:
                        _, _, _, clt, rid = decode_record(rec)
                    except Exception:                    # noqa: BLE001
                        continue
                    if clt == self.clt_id and lo <= rid <= hi \
                            and self._routed_hi.get(clt, 0) >= rid:
                        candidates.append((rid, rec))
            for rid, rec in candidates:
                key = (self.clt_id, rid)
                if key not in self._nack_replayed:
                    self._nack_replayed.add(key)
                    action, conn_id, data, _, _ = decode_record(rec)
                    to_replay.append((action, conn_id, data))
            # Lossless pruning: own records commit in req_id order (the
            # proxy numbers in submit order and aborted records never
            # enter the log), so once the endpoint DB's last applied
            # req for this bridge passes a range's hi, every member is
            # resolved — committed ones were handled above or at their
            # _on_commit, the rest can never commit.
            ep = self.daemon.node.epdb.search(self.clt_id)
            if ep is not None:
                self._nacked = [(a, b) for a, b in self._nacked
                                if b > ep.last_req_id]
                self._nack_replayed = {
                    (c, r) for c, r in self._nack_replayed
                    if any(a <= r <= b for a, b in self._nacked)}
            if len(self._nacked) > 4096:
                # Backstop only (a storm of >4096 UNRESOLVED failed
                # reads): dropping a live range risks silent app
                # divergence, so account loudly instead of trimming
                # quietly.
                self.daemon.node.stats["nack_ranges_dropped"] = \
                    self.daemon.node.stats.get("nack_ranges_dropped", 0) \
                    + len(self._nacked) - 4096
                if self.logger is not None:
                    self.logger.error(
                        "NACK range backstop hit: dropping %d oldest "
                        "ranges (app may need a re-prime)",
                        len(self._nacked) - 4096)
                self._nacked = self._nacked[-4096:]
        for action, conn_id, data in to_replay:
            self.replayer.submit(action, conn_id, data)

    def _is_nacked(self, rec: int) -> bool:
        return any(lo <= rec <= hi for lo, hi in self._nacked)

    def _submit(self, action: int, conn_id: int, cur_rec: int,
                data: bytes) -> None:
        payload = encode_record(action, conn_id, data,
                                clt_id=self.clt_id, req_id=cur_rec)
        with self._sub_lock:
            self._last_submitted = max(self._last_submitted, cur_rec)
        with self.daemon.lock:
            pr = self.daemon.node.submit(cur_rec, self.clt_id, payload)
        if pr is None:
            # Not leader (anymore): the record can't commit through us.
            # Release the spinning app thread; the client will observe
            # failover semantics and retry (reference behavior: capture
            # is leader-gated, proxy.c:108).
            self._release(cur_rec, abort=True)
        elif pr.reply is not None:
            # Duplicate of an already-applied record (daemon restarted
            # and replayed its durable store): already released.
            self._release(cur_rec)

    # -- role mirror + abort sweep (runs in the daemon tick, under the
    # node lock) ----------------------------------------------------------

    def _mirror_role(self) -> None:
        """Mirror role/term into shm for the proxy's capture gate, and
        release records stranded by leadership loss (they can no longer
        commit through this replica; the spinning app thread proceeds
        and the client observes failover semantics)."""
        node = self.daemon.node
        self._shm_set(_OFF_IS_LEADER, 1 if node.is_leader else 0)
        self._shm_set(_OFF_TERM, node.current_term)
        # FindLeader hint (leader slot + 1; 0 = unknown): a refused
        # misdirected client's operator reads where leadership went
        # straight out of shm instead of grepping logs (run.sh:46-68).
        hint = node.idx if node.is_leader else node.leader_hint
        self._shm_set(_OFF_LEADER_HINT, 0 if hint is None else hint + 1)
        # Surface proxy-side spin timeouts (proxy.cpp wait_released):
        # each one is a reply the app sent for a record consensus never
        # released — invisible divergence unless accounted here.
        spins = self._shm_get(_OFF_SPIN_TIMEOUTS)
        if spins > self._spin_timeouts_seen:
            node.stats["proxy_spin_timeouts"] = spins
            if self.logger is not None:
                self.logger.error(
                    "proxy proceeded on %d unreleased record(s) (spin "
                    "timeout): app replies may precede replication",
                    spins - self._spin_timeouts_seen)
            self._spin_timeouts_seen = spins
        if not node.is_leader:
            with self._sub_lock:
                last = self._last_submitted
            covered = max(self.highest_rec,
                          self._shm_get(_OFF_ABORT_FLOOR))
            if covered < last:
                self._release(last, abort=True)

    def _reprime_records(self) -> list[tuple[int, int, bytes]]:
        """Record history for a dirty-app rebuild (Replayer._reprime):
        every bridge-captured record in the relay SM, minus this app
        incarnation's own live captures (the app executed those bytes
        itself when the capture was released) — the same skip set the
        snapshot prime uses (_on_snapshot)."""
        with self.daemon.lock:
            records = self._sm_records()
            self.daemon.node.bump("replay_reprimes")
        out: list[tuple[int, int, bytes]] = []
        for rec in records:
            try:
                action, conn_id, data, clt, rid = decode_record(rec)
            except Exception:                            # noqa: BLE001
                continue
            if not is_bridge_clt(clt):
                continue
            if clt == self.clt_id and rid >= self._boot_base:
                continue
            out.append((action, conn_id, data))
        return out

    # -- commit upcall ----------------------------------------------------

    def _sm_records(self) -> list[bytes]:
        """Full record dump from the relay SM, spill-mode aware
        (iter_records); empty for non-relay SMs."""
        sm = self.daemon.node.sm
        it = getattr(sm, "iter_records", None)
        if it is not None:
            return it()
        return list(getattr(sm, "records", []))

    def _index_own(self, rid: int, rec: bytes) -> None:
        """Index an own routed record for O(range) NACK resolution
        (caller holds the daemon lock)."""
        self._own_routed[rid] = rec
        while len(self._own_routed) > self._OWN_ROUTED_CAP:
            old, _ = self._own_routed.popitem(last=False)
            if old > self._own_routed_floor:
                self._own_routed_floor = old

    def _on_snapshot(self, snap, ep_dump) -> None:
        """A leader-pushed snapshot replaced the relay SM wholesale:
        prime the local app with the snapshot-covered records it has NOT
        executed yet.  Three classes are skipped: records already routed
        through _on_commit (a live replica that merely fell behind the
        pruned head has executed that prefix), records this app
        incarnation captured live (req_id >= the boot base — the app
        executed the bytes itself when the capture was released), and
        non-bridge payloads (KVS client commands have no app to replay
        into).  A fresh joiner's empty routed frontier means full replay,
        matching the reference's proxy_apply_db_snapshot (proxy.c:306)."""
        records = self._sm_records()
        for rec in records:
            try:
                action, conn_id, data, clt, rid = decode_record(rec)
            except Exception:
                continue
            if not is_bridge_clt(clt):
                continue
            if self._routed_hi.get(clt, 0) >= rid:
                continue
            self._routed_hi[clt] = rid
            if clt == self.clt_id:
                self._index_own(rid, rec)
            if clt == self.clt_id and rid >= self._boot_base \
                    and not self._is_nacked(rid):
                # Our own live capture, now committed under the snapshot:
                # the app executed the bytes itself — release the spin
                # instead of replaying.  (NACKed captures were NOT
                # executed — those fall through to the replay below.)
                self._release(rid)
                continue
            if clt == self.clt_id:
                self._nack_replayed.add((clt, rid))
            self.replayer.submit(action, conn_id, data)

    def _on_commit(self, e: LogEntry) -> None:
        """Committed-entry routing (apply_committed_entries' proxy calls,
        dare_server.c:1953-1955): our own records release the captured
        app thread; records captured elsewhere replay into the local app."""
        if e.type != EntryType.CSM or not is_bridge_clt(e.clt_id):
            return
        key = (e.clt_id, e.req_id)
        if self._routed_hi.get(e.clt_id, 0) >= e.req_id:
            return                    # already primed via snapshot replay
        self._routed_hi[e.clt_id] = e.req_id
        if e.clt_id == self.clt_id:
            self._index_own(e.req_id, e.data)
            if self._is_nacked(e.req_id) and key not in self._nack_replayed:
                # The proxy FAILED the app's read that carried this
                # record (leadership lost mid-flight), yet the record
                # committed anyway (the new leader preserved it): our
                # own app never executed these bytes — replay them
                # locally like a foreign record, or this app alone
                # would miss a committed write.
                self._nack_replayed.add(key)
                action, conn_id, data, _, _ = decode_record(e.data)
                self.replayer.submit(action, conn_id, data)
            self._release(e.req_id)
        else:
            action, conn_id, data, _, _ = decode_record(e.data)
            self.replayer.submit(action, conn_id, data)


#: Repo-root native build artifacts (single source of truth; appcluster
#: and the benchmark harness import these).
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_BUILD = os.path.join(REPO_ROOT, "native", "build")
INTERPOSE_SO = os.path.join(NATIVE_BUILD, "interpose.so")


def proxy_env(bridge: Bridge, log_path: Optional[str] = None,
              spin_timeout_ms: Optional[int] = None) -> dict[str, str]:
    """Environment for launching an app under the interposer against
    this bridge (the run.sh:23-31 env-var analog)."""
    env = {
        "LD_PRELOAD": INTERPOSE_SO,
        "APUS_BRIDGE_SOCK": bridge.sock_path,
        "APUS_BRIDGE_SHM": bridge.shm_path,
    }
    if log_path is not None:
        env["APUS_PROXY_LOG"] = log_path
    if spin_timeout_ms is not None:
        env["APUS_SPIN_TIMEOUT_MS"] = str(spin_timeout_ms)
    return env
