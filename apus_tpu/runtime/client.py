"""Client service + client library.

The reference's clients reach the group over UD datagrams
(client_req_t/client_rep_t, dare_ibv_ud.h:60-81; handled in
handle_message_from_client, dare_ibv_ud.c:863-944) — under APUS proper
the "client" is the proxy, but the DARE client path (CLT_WRITE/CLT_READ)
is fully present.  This module is that path over the DCN:

- daemon side: two extra ops on the replica's PeerServer port —
  CLT_WRITE (submit, block until applied, return the SM reply) and
  CLT_READ (linearizable read).  Non-leaders answer NOT_LEADER with a
  hint, the leader-redirect analog of clients multicasting until they
  find the leader.
- ``ApusClient``: retrying client with per-client monotone req_ids;
  safe to retry across failovers because the server dedups on
  (clt_id, req_id) (exactly-once; see apus_tpu.core.epdb).
"""

from __future__ import annotations

import os
import secrets
import socket
import threading
import time
from typing import Optional

from apus_tpu.models.sm import REFUSED_REPLY_PREFIX as _REFUSED_PREFIX
from apus_tpu.parallel import wire

ST_ERROR = wire.ST_ERROR

OP_CLT_WRITE = 16
OP_CLT_READ = 17
OP_STATUS = 18
OP_MAINT_READS = 19   # flip the proxy's stale-follower-reads gate

ST_NOT_LEADER = 4
ST_TIMEOUT = 5
#: Elastic-group bounces (runtime/elastic.py).  WRONG_GROUP: the key's
#: bucket is owned by another consensus group — the reply carries the
#: owner gid AND the full shard map (epoch-versioned), so one bounce
#: re-synchronizes a stale-epoch client; the server-side refusal is
#: deterministic (the op never applied here), so the client re-routes
#: under a FRESH req_id and exactly-once holds at the owner.
#: MIGRATING: the bucket is frozen mid-migration — retry shortly, same
#: group (the flip resolves it to OK or WRONG_GROUP).
ST_WRONG_GROUP = 8
ST_MIGRATING = 9

# Typed overload shed (ISSUE 17, runtime/overload.py): the op was
# REFUSED admission before touching any log — a deterministic refusal
# like WRONG_GROUP, retry-safe under the SAME req_id (nothing was
# submitted, so exactly-once cannot double-apply).  The reply body
# carries a u32 LE retry-after hint in milliseconds.
from apus_tpu.runtime.overload import (ST_OVERLOAD,  # noqa: E402
                                       CircuitBreaker, Overloaded,
                                       RetryBudget, backoff_s,
                                       parse_retry_after, shed_reply)


def _elastic_bounce(daemon, node, req_id: int, verdict) -> bytes:
    """Typed elastic bounce reply (caller holds the daemon lock)."""
    if verdict[0] == "migrating":
        return wire.u8(ST_MIGRATING) + wire.u64(req_id)
    m = daemon.elastic.shard_map()
    return (wire.u8(ST_WRONG_GROUP) + wire.u64(req_id)
            + wire.u8(verdict[1]) + wire.blob(m.to_blob()))


def _sentinel_bounce(daemon, node, req_id: int, data: bytes,
                     reply: bytes) -> bytes:
    """Translate a deterministic REFUSED apply (a write that raced a
    leader change past an unapplied migration record and no-op'd at
    apply; sm.REFUSED_REPLY_PREFIX) into the matching typed bounce.
    Caller holds the daemon lock."""
    from apus_tpu.models.kvs import REFUSED_DEPARTED
    if reply == REFUSED_DEPARTED and daemon.elastic is not None:
        v = daemon.elastic.departed(node, data)
        if v is not None:
            return _elastic_bounce(daemon, node, req_id, v)
    return wire.u8(ST_MIGRATING) + wire.u64(req_id)


def _txn_passthrough(reply: "bytes | None") -> bool:
    """True for REFUSED_TX-prefixed replies (transaction-prepare/
    decide refusals): these must reach the txn DRIVER verbatim as
    OK-status replies — translating them into typed bounces would
    strand the driver in a retry loop with no refusal reason."""
    from apus_tpu.models.kvs import REFUSED_TX
    return reply is not None and reply.startswith(REFUSED_TX)


def _read_locked(reply: "bytes | None") -> bool:
    """True when a read resolved to the txn WRITE-lock sentinel: the
    key sits under a prepared transaction's buffered write, so serving
    the pre-txn value could be a stale read (the txn may already be
    decided-commit at the coordinator).  Exact equality, not prefix —
    GET replies are raw stored values and must never be misbounced."""
    from apus_tpu.models.kvs import REFUSED_LOCKED
    return reply == REFUSED_LOCKED


def _svc_emulate(daemon, n_reads: int) -> None:
    """Per-replica read service-capacity emulation (bench.py
    --throughput follower-read rows): each served read holds this
    daemon's service gate for APUS_READ_SVC_US microseconds, modeling a
    replica that owns one core on boxes that don't have one per
    process.  Runs OUTSIDE the node lock (the gate serializes read
    service per replica, nothing else).  Off (zero overhead) unless the
    bench armed it."""
    svc = getattr(daemon, "read_svc", 0.0)
    if svc and n_reads > 0:
        with daemon._svc_gate:
            time.sleep(svc * n_reads)


def _wsvc_emulate(daemon, gid: int, n_writes: int) -> None:
    """Per-GROUP write service-capacity emulation (bench.py --throughput
    --groups): each admitted write holds its group's service gate for
    APUS_WRITE_SVC_US microseconds at the leader, modeling a deployment
    where every group's leader owns one core (the write-path sibling of
    ``_svc_emulate``).  Gates are per gid, so different groups' service
    runs in parallel — exactly the sharding the aggregate-throughput
    claim is about.  Off (zero overhead) unless the bench armed it."""
    svc = getattr(daemon, "write_svc", 0.0)
    if svc and n_writes > 0:
        gate = daemon._wsvc_gates.setdefault(gid, threading.Lock())
        with gate:
            time.sleep(svc * n_writes)


def make_client_ops(daemon, node=None) -> dict:
    """Extra PeerServer ops for a ReplicaDaemon (runs on per-connection
    server threads; blocking a handler blocks only that client's
    connection).  ``node`` binds the handlers to one consensus group's
    node (multi-group daemons build one table per group, dispatched by
    the OP_GROUP demux); None = the primary group."""
    node = node if node is not None else daemon.node

    def clt_write(r: wire.Reader) -> bytes:
        req_id, clt_id = r.u64(), r.u64()
        data = r.blob()
        obs = daemon.obs
        sp = obs.spans if obs is not None else None
        traced = sp is not None and sp.sampled(req_id)
        if traced:
            sp.stamp(clt_id, req_id, "ingest")
        el = daemon.elastic
        with daemon.lock:
            if traced:
                sp.stamp(clt_id, req_id, "lock")
            if el is not None:
                # Elastic-group admission fence: bucket owned by
                # another group (WRONG_GROUP + map) or frozen
                # mid-migration (MIGRATING).  Dedup still wins: a
                # retried already-applied req answers from the cache
                # via submit below (admit only refuses keys this
                # group cannot serve NOW, and an applied write's key
                # was owned when it applied).
                if node.epdb.duplicate_of_applied(clt_id, req_id) \
                        is None:
                    v = el.admit(node, data)
                    if v is not None:
                        return _elastic_bounce(daemon, node, req_id, v)
            pr = node.submit(req_id, clt_id, data)
            if traced:
                sp.stamp(clt_id, req_id, "admit")
        if pr is None:
            return _not_leader(daemon, req_id, node=node)
        deadline = time.monotonic() + daemon.client_op_timeout
        with daemon.commit_cond:
            while True:
                # Ack ONLY on the reply sentinel (set when this client's
                # entry applied) — apply position alone can be satisfied
                # by a different entry after truncation.
                if pr.reply is not None:
                    if _txn_passthrough(pr.reply):
                        # Prepare/decide refusal: verbatim to the txn
                        # driver (OK status; never a bounce).
                        return (wire.u8(wire.ST_OK) + wire.u64(req_id)
                                + wire.blob(pr.reply))
                    if pr.reply.startswith(_REFUSED_PREFIX):
                        # Raced a leader change past an unapplied
                        # migration/lock record; deterministically
                        # no-op'd.
                        return _sentinel_bounce(daemon, node, req_id,
                                                data, pr.reply)
                    if traced:
                        sp.stamp(clt_id, req_id, "reply", idx=pr.idx)
                        sp.finish(clt_id, req_id)
                    break
                if not node.is_leader:
                    return _not_leader(daemon, req_id, node=node)
                left = deadline - time.monotonic()
                if left <= 0:
                    return wire.u8(ST_TIMEOUT) + wire.u64(req_id)
                daemon.commit_cond.wait(min(left, 0.25))
        _wsvc_emulate(daemon, node.gid, 1)
        return (wire.u8(wire.ST_OK) + wire.u64(req_id)
                + wire.blob(pr.reply))

    def clt_read(r: wire.Reader) -> bytes:
        req_id, clt_id = r.u64(), r.u64()
        data = r.blob()
        el = daemon.elastic
        with daemon.lock:
            if el is not None:
                # Ownership fence: reads on FROZEN buckets still serve
                # (nothing can modify them anywhere until the flip);
                # buckets owned elsewhere bounce with the map.
                v = el.admit(node, data)
                if v is not None and v[0] == "wrong_group":
                    return _elastic_bounce(daemon, node, req_id, v)
            rr = node.read(req_id, clt_id, data)
            if rr is None:
                # Not the leader: try the follower-lease local-read
                # path (core/node.py follower_read) before bouncing.
                rr = node.follower_read(req_id, clt_id, data)
        if rr is None:
            return _not_leader(daemon, req_id, node=node)
        follower = getattr(rr, "flr", False)
        deadline = time.monotonic() + daemon.client_op_timeout
        with daemon.commit_cond:
            while True:
                if rr.done:
                    if rr.error:
                        return wire.u8(wire.ST_ERROR) + wire.u64(req_id)
                    if _read_locked(rr.reply):
                        # Key under a prepared txn's buffered write:
                        # transient bounce, retried past the TC/TA.
                        return (wire.u8(ST_MIGRATING)
                                + wire.u64(req_id))
                    if el is not None:
                        # Reply-time re-check: the bucket may have
                        # DEPARTED while the read was parked — serving
                        # the locally-applied value past the flip
                        # would be a stale read.
                        v = el.departed(node, data)
                        if v is not None:
                            return _elastic_bounce(daemon, node,
                                                   req_id, v)
                    break           # served; svc gate OUTSIDE the lock
                if getattr(rr, "refused", False):
                    # Lease lapsed/invalidated under the parked read:
                    # typed bounce; the client retries at the leader.
                    return _not_leader(daemon, req_id, node=node)
                if not follower and not node.is_leader:
                    return _not_leader(daemon, req_id, node=node)
                left = deadline - time.monotonic()
                if left <= 0:
                    return wire.u8(ST_TIMEOUT) + wire.u64(req_id)
                daemon.commit_cond.wait(min(left, 0.25))
        _svc_emulate(daemon, 1)
        return (wire.u8(wire.ST_OK) + wire.u64(req_id)
                + wire.blob(rr.reply or b""))

    def status(r: wire.Reader) -> bytes:
        """Observability probe (ops tooling / process launchers): role,
        term, log offsets — the information run.sh greps out of server
        logs ("[T%d] LEADER" banners, run.sh:46-68), as a queryable op."""
        import json

        from apus_tpu.core.cid import CidState
        from apus_tpu.core.types import EntryType
        with daemon.lock:
            n = daemon.node
            # Sender-side snapshot-stream counters live on the REAL
            # transport (the fault plane proxies everything else).
            _t = daemon.transport
            _tstats = getattr(getattr(_t, "inner", _t), "stats", {})
            config_in_flight = any(e.type == EntryType.CONFIG
                                   for e in n.log.entries(n.log.apply))
            st = {
                "idx": daemon.idx,
                "role": n.role.name,
                "is_leader": n.is_leader,
                "term": n.current_term,
                "leader_hint": n.leader_hint,
                # Actionable FindLeader answer (run.sh:46-68 greps
                # logs; here ANY replica's status names the leader's
                # control endpoint): clients/harnesses reattach from
                # the hint instead of scanning the whole peer table.
                "leader_addr": (
                    daemon.spec.peers[n.idx] if n.is_leader
                    and n.idx < len(daemon.spec.peers)
                    else daemon.spec.peers[n.leader_hint]
                    if n.leader_hint is not None
                    and n.leader_hint < len(daemon.spec.peers)
                    else None),
                "commit": n.log.commit,
                "apply": n.log.apply,
                "end": n.log.end,
                "log_head": n.log.head,
                "epoch": n.cid.epoch,
                "group_size": n.cid.size,
                "members": [i for i in range(n.cid.extended_group_size)
                            if n.cid.contains(i)],
                # Reconfiguration observability: the churn nemesis,
                # operators, and tests assert convergence on these
                # fields instead of log-scraping — the full cid (state
                # + resize target + bitmask), whether ANY membership
                # change is still in flight (a non-STABLE cid OR an
                # unapplied CONFIG entry), snapshot pushes in
                # progress, this replica's incarnation, and the
                # graceful-leave drain state.
                "cid_state": n.cid.state.name,
                "cid_new_size": n.cid.new_size,
                "cid_bitmask": n.cid.bitmask,
                "config_in_flight": config_in_flight,
                "mid_resize": (n.cid.state != CidState.STABLE
                               or config_in_flight),
                "snap_pushing": sorted(n._snap_pushing),
                "snapshots_pushed": n.stats.get("snapshots_pushed", 0),
                "snapshots_installed": n.stats.get(
                    "snapshots_installed", 0),
                # Snapshot-transfer view (large-state recovery plane):
                # chunk progress + resume counters from the SENDER
                # transport, receiver-side stream resumes/quarantines,
                # delta-snapshot traffic both ways, per-peer push
                # generations, and the store's compaction floor — so
                # the churn nemesis and wait helpers assert RESUME
                # (never restart-from-zero) behavior over the wire
                # instead of log-scraping.
                "snap_chunks_sent": _tstats.get("snap_chunks_sent", 0),
                "snap_chunks_acked": _tstats.get("snap_chunks_acked",
                                                 0),
                "snap_resumes": _tstats.get("snap_resumes", 0),
                "snap_resumed_bytes": _tstats.get("snap_resumed_bytes",
                                                  0),
                "snap_stream_resumes_rx": n.stats.get(
                    "snap_stream_resumes", 0),
                "snap_chunk_quarantines": n.stats.get(
                    "snap_chunk_quarantines", 0),
                "snap_push_abandoned": n.stats.get(
                    "snap_push_abandoned", 0),
                "snap_generation": dict(n._snap_push_gen),
                "delta_snapshots": n.stats.get("delta_snapshots", 0),
                "delta_installs": n.stats.get("delta_installs", 0),
                "delta_refused": n.stats.get("delta_refused", 0),
                "compaction_floor": (
                    daemon.persistence.compaction_floor
                    if getattr(daemon, "persistence", None) is not None
                    else 0),
                "compactions": (
                    daemon.persistence.compactions
                    if getattr(daemon, "persistence", None) is not None
                    else 0),
                "store_records_since_base": (
                    daemon.persistence.entries_since_base
                    if getattr(daemon, "persistence", None) is not None
                    else None),
                "incarnation": n.incarnation,
                "draining": getattr(daemon, "draining", False),
                "auto_removes": n.stats.get("auto_removes", 0),
                "graceful_leaves": n.stats.get("graceful_leaves", 0),
                "resize_aborts": n.stats.get("resize_aborts", 0),
                "fenced_ctrl_writes": n.stats.get("fenced_ctrl_writes",
                                                  0),
                # Relay-SM record dump size (leak/ops gauge; the soak
                # watches it) — absent for non-relay SMs.
                "sm_records": getattr(n.sm, "record_count", None),
                "sm_record_bytes": getattr(n.sm, "record_bytes", None),
                # Throughput-path observability: lease-served vs
                # read-index-verified reads, and group-commit coalescing
                # (drain windows vs entries admitted through them).
                "lease_reads": n.stats.get("lease_reads", 0),
                "readindex_verifies": n.stats.get("readindex_verifies", 0),
                "lease_renewals": n.stats.get("lease_renewals", 0),
                # Follower-read-lease observability (read scale-out):
                # grants issued (leader) / local reads served and
                # bounces (follower) / commit advances held back by a
                # live holder's missing ack / pause- or jump-induced
                # lapses, plus whether THIS replica currently holds a
                # serveable lease and whether its clock is skewed by
                # the adversarial-time nemesis.
                "flr_grants": n.stats.get("flr_grants", 0),
                "flr_grant_refusals": n.stats.get("flr_grant_refusals",
                                                  0),
                "flr_local_reads": n.stats.get("flr_local_reads", 0),
                "flr_forwards": n.stats.get("flr_forwards", 0),
                "flr_renewals": n.stats.get("flr_renewals", 0),
                "flr_lapses": n.stats.get("flr_lapses", 0),
                "flr_pause_lapses": n.stats.get("flr_pause_lapses", 0),
                "flr_epoch_refusals": n.stats.get("flr_epoch_refusals",
                                                  0),
                "flr_commit_blocked": n.stats.get("flr_commit_blocked",
                                                  0),
                # Bucket-granular lease view: commit advances a
                # whole-log rule would have blocked, bucket-scoped
                # grants issued, reads bounced for read-set coverage,
                # and the held lease's set size (-1 = full set).
                "flr_commit_bypass": n.stats.get("flr_commit_bypass",
                                                 0),
                "flr_bucket_grants": n.stats.get("flr_bucket_grants",
                                                 0),
                "flr_bucket_refusals": n.stats.get(
                    "flr_bucket_refusals", 0),
                "flr_lease_buckets": (-1 if n._flease_buckets is None
                                      else len(n._flease_buckets)),
                "flr_lease_live": bool(
                    n._flease_ok(n._fresh_now())[0]),
                "clock_skewed": bool(getattr(daemon.clock, "skewed",
                                             False)),
                "drain_windows": n.stats.get("drain_windows", 0),
                "drain_entries": n.stats.get("drain_entries", 0),
                "repl_windows": n.stats.get("repl_windows", 0),
                # Wire-ingest coalescing (PeerServer burst drains):
                # frames/batch is the direct proof pipelined clients
                # coalesce on the wire — the de-flaked throughput
                # smoke asserts on these instead of wall clock.
                "ingest_batches": daemon.server.stats.get(
                    "ingest_batches", 0),
                "ingest_frames": daemon.server.stats.get(
                    "ingest_frames", 0),
                "ingest_solo": daemon.server.stats.get("ingest_solo",
                                                       0),
                # Observability plane: OP_METRICS/OP_OBS_DUMP served?
                "obs": daemon.obs is not None,
                # Disk-fault containment observability: I/O errors on
                # the persistence path and whether they disabled it
                # (the replica keeps serving; see daemon._persist_fail).
                "persist_errors": getattr(daemon, "persist_errors", 0),
                "persist_disabled": getattr(daemon, "persist_disabled",
                                            False),
                "persist_syncs": (daemon.persistence.syncs
                                  if getattr(daemon, "persistence", None)
                                  is not None else None),
            }
            # Multi-group (Multi-Raft) observability: per-group
            # role/term/offsets/config so harnesses assert PER-GROUP
            # convergence (different groups may have different
            # leaders) over the wire instead of log-scraping.
            st["n_groups"] = getattr(daemon, "n_groups", 1)
            if getattr(daemon, "groupset", None) is not None:
                st["groups"] = daemon.groupset.status_view()
            # Elastic-group observability: the derived shard-map epoch
            # (the client router's "hash epoch") and every migration
            # record any local SM knows, with its state — harnesses
            # assert split/merge completion over the wire on these.
            el = getattr(daemon, "elastic", None)
            if el is not None:
                st["router_epoch"] = el.shard_map().epoch
                st["migrations"] = el.migrations_view()
            # Transaction observability (runtime/txn.py): open/decided
            # coordinator records + prepared participant records +
            # lock counts (failure dumps attach this beside the
            # groups/router views), and the 2PC counters.
            txn = getattr(daemon, "txn", None)
            if txn is not None:
                st["txns"] = txn.txns_view()
                _tn = (daemon.groupset.nodes
                       if daemon.groupset is not None else [n])
                # Distinct stats views only: with a shared obs hub
                # every group's node rebinds onto ONE "node" view, and
                # summing per node would multiply the counts.
                _tv = list({id(x.stats): x.stats for x in _tn}.values())
                for f in ("txn_prepared", "txn_decided", "txn_aborted",
                          "txn_resumed", "txn_lock_conflicts",
                          "txn_epoch_aborts", "txn_batches"):
                    st[f] = sum(v.get(f, 0) for v in _tv)
            # Native data-plane observability (parallel/native_plane):
            # the C loop's counter snapshot + adoption state, so
            # harnesses assert "the native path actually engaged"
            # over the wire instead of poking daemon internals.
            if getattr(daemon, "native", None) is not None:
                st["native_plane"] = daemon.native.status_view()
            # Overload control plane (ISSUE 17): budgets, live/peak
            # queue depth, shed-by-reason counters with the native
            # plane's shed mirror folded in — the failure-dump and
            # saturation-campaign assertion surface.
            ovl = getattr(daemon, "overload", None)
            if ovl is not None:
                st["overload"] = ovl.status(st.get("native_plane"))
            # Misdirection-gate observability (bridged replicas): how
            # many non-leader client reads the proxy refused.
            refusals = getattr(daemon, "misdirect_refusals", None)
            if refusals is not None:
                st["misdirect_refusals"] = refusals()
            # Device-plane observability (in-process or mesh): did
            # commits ride the device quorum, and is the plane alive?
            drv = daemon.device_driver
            if drv is not None:
                runner = drv.runner
                st["devplane"] = {
                    "ready": getattr(runner, "ready", True),
                    "dead": getattr(runner, "dead", False),
                    "death_reason": getattr(runner, "death_reason", None),
                    "rounds": runner.stats.get("rounds", 0),
                    "resets": runner.stats.get("resets", 0),
                    "poisoned": runner.stats.get("poisoned_rounds", 0),
                    "drained": drv.stats.get("drained", 0),
                    "fallbacks": drv.stats.get("fallbacks", 0),
                    "commits": n.stats.get("devplane_commits", 0),
                    "owns_commit": n.external_commit,
                    # Re-formation observability (mesh runners): the
                    # plane epoch this process last joined, its clique,
                    # whether a rebuild is in flight, and how many
                    # epochs this process has joined.
                    "epoch": getattr(runner, "epoch", None),
                    "members": list(getattr(runner, "members", []))
                    or None,
                    "building": getattr(runner, "building", False),
                    "build_target": (getattr(runner, "_build_target", -1)
                                     if getattr(runner, "building", False)
                                     or getattr(runner, "_build_target",
                                                -1) >= 0 else None),
                    "reforms": runner.stats.get("reforms", 0),
                }
        return wire.u8(wire.ST_OK) + wire.blob(json.dumps(st).encode())

    def maint_reads(r: wire.Reader) -> bytes:
        """Maintenance switch: allow/refuse stale client reads on this
        replica's raw app while it is not the leader (the proxy's
        misdirection gate, apus_wire.h follower_reads).  Verification
        harnesses flip it AFTER traffic ends to inspect replica state."""
        allow = r.u8() != 0
        setter = getattr(daemon, "follower_reads_setter", None)
        if setter is None:
            return wire.u8(wire.ST_ERROR)    # no bridge on this daemon
        setter(allow)
        return wire.u8(wire.ST_OK)

    return {OP_CLT_WRITE: clt_write, OP_CLT_READ: clt_read,
            OP_STATUS: status, OP_MAINT_READS: maint_reads}


def make_client_batch_hook(daemon):
    """Pipelined-burst handler for the daemon's PeerServer
    (PeerServer.batch_hook): a burst of CLT_WRITE/CLT_READ frames is
    admitted under ONE node-lock acquisition — group-commit admission:
    op i+1 enters the log window before op i's commit, so K pipelined
    ops share ~one replication round instead of paying K — and then
    runs ONE commit wait for the whole window, replying in request
    order.  Returns None (decline -> sequential dispatch) when the
    burst contains any non-client op.

    Program order WITHIN a burst (redis-pipeline read-your-write): a
    read observes every write that precedes it in the same burst.  The
    burst's writes are flushed into the log at admission
    (Node.flush_pending) and each read registers with a wait_idx floor
    just past its preceding writes' indices; a read whose preceding
    write could not enter the log yet (transiently full ring) defers
    registration to the wait loop, re-tried on each wake (the wake
    tuple covers log.end, so the append itself wakes us)."""

    def hook(frames: list[bytes]):
        # Multi-group bursts: frames may arrive OP_GROUP-wrapped —
        # each op carries its gid, admitted against ITS group's node.
        # One lock acquisition and one commit-wait loop still cover
        # the WHOLE burst, so the leader's group-commit drain
        # amortizes across every group with queued ops.
        arrival = time.monotonic()
        parsed = []
        for f in frames:
            r = wire.Reader(f)
            op = r.u8()
            gid = 0
            if op == wire.OP_GROUP:
                gid = r.u8()
                op = r.u8()
            if op not in (OP_CLT_WRITE, OP_CLT_READ):
                return None
            parsed.append((op, r.u64(), r.u64(), r.blob(), gid))
        return run(parsed, arrival)

    def run_parsed(items, arrival=None):
        """Native-plane entry (parallel.native_plane): the C++ ingest
        loop hands bursts PRE-PARSED — ``(gid, op, req_id, clt_id,
        data)`` with the payload slices already cut — so admission
        skips the Python wire re-parse entirely.  Same admission, same
        replies, byte-identical wire behavior."""
        return run([(op, rid, cid, data, gid)
                    for gid, op, rid, cid, data in items], arrival)

    def run(parsed, arrival=None):
        nodes = [daemon.group_node(g) for (_o, _r, _c, _d, g) in parsed]
        handles: list = [None] * len(parsed)
        registered = [False] * len(parsed)
        # Per-op stage spans (write ops, req_id-sampled): the whole
        # burst shares one ingest/lock stamp time — stamps here are
        # batch-granular by design (that IS the group-commit shape).
        obs = daemon.obs
        sp = obs.spans if obs is not None else None
        traced: list[int] = []
        if sp is not None:
            t_ingest = sp.now()
            for i, (op, rid, cid_, _d, _g) in enumerate(parsed):
                if op == OP_CLT_WRITE and sp.sampled(rid):
                    sp.stamp(cid_, rid, "ingest", t=t_ingest)
                    traced.append(i)

        def _register_read(i: int) -> None:
            """Register read i once every preceding SAME-GROUP write of
            the burst holds a log index (caller holds the node lock).
            Program order — and read-your-write — is a WITHIN-group
            contract; cross-group ops interleave freely (each group is
            an independent log).  Usually immediate; deferred only
            while the ring is full."""
            node = nodes[i]
            if node is None:
                registered[i] = True      # unknown gid: resolves ERROR
                return
            el = daemon.elastic
            if el is not None:
                v = el.admit(node, parsed[i][3])
                if v is not None and v[0] == "wrong_group":
                    replies[i] = _elastic_bounce(daemon, node,
                                                 parsed[i][1], v)
                    registered[i] = True
                    return
            floor = 0
            for j in range(i):
                h = handles[j]
                if parsed[j][0] != OP_CLT_WRITE or h is None \
                        or parsed[j][4] != parsed[i][4]:
                    continue        # reads don't gate; None -> not-leader
                if h.idx is None:
                    return          # not in the log yet: retry on wake
                floor = max(floor, h.idx + 1)
            op, req_id, clt_id, data, _gid = parsed[i]
            handles[i] = node.read(req_id, clt_id, data,
                                   min_wait_idx=floor)
            if handles[i] is None:
                # Not the leader: the follower-lease local-read path
                # (burst writes all bounce NOT_LEADER; floor is 0).
                handles[i] = node.follower_read(req_id, clt_id, data)
            registered[i] = True

        replies: list = [None] * len(parsed)
        with daemon.lock:
            # Deadline-aware shed at the group-commit drain (ISSUE 17):
            # the burst queued so long for the node lock that its
            # client deadline already expired — submitting it would
            # burn replication rounds on replies nobody will read,
            # exactly the work amplification that makes overload
            # metastable.  Dropped BEFORE admission: nothing entered
            # any log, so exactly-once and the audit plane's ambiguity
            # rules are untouched (the typed shed is a deterministic
            # refusal; the client retries under the same req_id).
            ovl = getattr(daemon, "overload", None)
            if ovl is not None and arrival is not None \
                    and ovl.deadline_s > 0 \
                    and time.monotonic() - arrival >= ovl.deadline_s:
                ovl.on_shed("deadline", len(parsed))
                return [shed_reply(p[1], ovl.retry_after_ms)
                        for p in parsed]
            if traced:
                t_lock = sp.now()
                for i in traced:
                    sp.stamp(parsed[i][2], parsed[i][1], "lock",
                             t=t_lock)
            flush_nodes = []
            el = daemon.elastic
            for i, (op, req_id, clt_id, data, _gid) in enumerate(parsed):
                if op == OP_CLT_WRITE and nodes[i] is not None:
                    if el is not None and nodes[i].epdb \
                            .duplicate_of_applied(clt_id, req_id) \
                            is None:
                        # Elastic admission fence, exactly as the
                        # single-op path (dedup-first).
                        v = el.admit(nodes[i], data)
                        if v is not None:
                            replies[i] = _elastic_bounce(
                                daemon, nodes[i], req_id, v)
                            registered[i] = True
                            continue
                    handles[i] = nodes[i].submit(req_id, clt_id, data)
                    registered[i] = True
                    if nodes[i] not in flush_nodes:
                        flush_nodes.append(nodes[i])
                elif op == OP_CLT_WRITE:
                    registered[i] = True  # unknown gid: resolves ERROR
            if traced:
                t_admit = sp.now()
                for i in traced:
                    sp.stamp(parsed[i][2], parsed[i][1], "admit",
                             t=t_admit)
            for node in flush_nodes:
                node.flush_pending()
            for i, (op, *_rest) in enumerate(parsed):
                if op == OP_CLT_READ:
                    _register_read(i)

        def _resolve(i: int) -> bool:
            """Reply for op i if it is decided (under the lock)."""
            op, req_id, _clt, _d, _gid = parsed[i]
            node = nodes[i]
            if node is None:
                replies[i] = wire.u8(ST_ERROR) + wire.u64(req_id)
                return True
            if not registered[i]:
                if not node.is_leader:
                    # Leadership moved before the read could register
                    # (its gating write will bounce too).
                    replies[i] = _not_leader(daemon, req_id, node=node)
                    return True
                _register_read(i)
                if not registered[i]:
                    return False
                if replies[i] is not None:
                    return True     # registration bounced (wrong_group)
            h = handles[i]
            if h is None:
                replies[i] = _not_leader(daemon, req_id, node=node)
                return True
            if op == OP_CLT_WRITE:
                # Reply-sentinel gate, exactly as the single-op path:
                # apply position alone can be satisfied by a DIFFERENT
                # entry after truncation.
                if h.reply is not None:
                    if _txn_passthrough(h.reply):
                        replies[i] = (wire.u8(wire.ST_OK)
                                      + wire.u64(req_id)
                                      + wire.blob(h.reply))
                        return True
                    if h.reply.startswith(_REFUSED_PREFIX):
                        replies[i] = _sentinel_bounce(
                            daemon, node, req_id, _d, h.reply)
                        return True
                    replies[i] = (wire.u8(wire.ST_OK) + wire.u64(req_id)
                                  + wire.blob(h.reply))
                    if sp is not None and sp.sampled(req_id):
                        # Reply built: close the span (folds the stage
                        # durations into the registry histograms).
                        sp.stamp(_clt, req_id, "reply", idx=h.idx)
                        sp.finish(_clt, req_id)
                    return True
                if not node.is_leader:
                    replies[i] = _not_leader(daemon, req_id, node=node)
                    return True
                return False
            if getattr(h, "refused", False):
                # Follower lease lapsed under the parked read.
                replies[i] = _not_leader(daemon, req_id, node=node)
                return True
            if h.done:
                if h.error:
                    replies[i] = wire.u8(wire.ST_ERROR) + wire.u64(req_id)
                elif _read_locked(h.reply):
                    # Key under a prepared txn's buffered write.
                    replies[i] = (wire.u8(ST_MIGRATING)
                                  + wire.u64(req_id))
                else:
                    if daemon.elastic is not None:
                        # Reply-time departed re-check (see clt_read).
                        v = daemon.elastic.departed(node, _d)
                        if v is not None:
                            replies[i] = _elastic_bounce(
                                daemon, node, req_id, v)
                            return True
                    replies[i] = (wire.u8(wire.ST_OK) + wire.u64(req_id)
                                  + wire.blob(h.reply or b""))
                return True
            if not getattr(h, "flr", False) and not node.is_leader:
                # Leader-path read stranded by a leadership move;
                # follower-lease reads keep waiting (they resolve
                # done/refused on the tick).
                replies[i] = _not_leader(daemon, req_id, node=node)
                return True
            return False

        def _finish():
            # Service-capacity emulation covers every read the burst
            # served locally (leader lease or follower lease alike)
            # and — per group — every write it committed; runs outside
            # the lock, after the replies are built.  Gated on the
            # knobs so unarmed runs pay nothing per burst.
            if getattr(daemon, "read_svc", 0.0):
                _svc_emulate(daemon, sum(
                    1 for i, (op, *_r) in enumerate(parsed)
                    if op == OP_CLT_READ and replies[i] is not None
                    and replies[i][:1] == wire.u8(wire.ST_OK)))
            if getattr(daemon, "write_svc", 0.0):
                per_gid: dict[int, int] = {}
                for i, (op, _r, _c, _d, gid) in enumerate(parsed):
                    if op == OP_CLT_WRITE and replies[i] is not None \
                            and replies[i][:1] == wire.u8(wire.ST_OK):
                        per_gid[gid] = per_gid.get(gid, 0) + 1
                if len(per_gid) <= 1:
                    for gid, n in per_gid.items():
                        _wsvc_emulate(daemon, gid, n)
                else:
                    # Different groups' service runs on DIFFERENT
                    # emulated cores even when one daemon leads both
                    # (a burst spanning groups must not serialize the
                    # per-group gates in this one handler thread —
                    # that would model one shared core, the opposite
                    # of what the gate exists to model).
                    ts = [threading.Thread(
                        target=_wsvc_emulate, args=(daemon, gid, n),
                        daemon=True) for gid, n in per_gid.items()]
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
            return replies

        deadline = time.monotonic() + daemon.client_op_timeout
        with daemon.commit_cond:
            while True:
                unresolved = [i for i in range(len(parsed))
                              if replies[i] is None and not _resolve(i)]
                if not unresolved:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    for i in unresolved:
                        if replies[i] is None:
                            replies[i] = (wire.u8(ST_TIMEOUT)
                                          + wire.u64(parsed[i][1]))
                    break
                daemon.commit_cond.wait(min(left, 0.25))
        return _finish()

    hook.run_parsed = run_parsed
    return hook


def set_follower_reads(addr: str, allow: bool,
                       timeout: float = 2.0) -> bool:
    """Flip one daemon's stale-follower-reads maintenance gate (see
    make_client_ops.maint_reads).  Returns True on success."""
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(timeout)
            conn.sendall(wire.frame(wire.u8(OP_MAINT_READS)
                                    + wire.u8(1 if allow else 0)))
            resp = wire.read_frame(conn)
    except (OSError, ConnectionError, ValueError):
        return False
    return bool(resp) and resp[0] == wire.ST_OK


def probe_status(addr: str, timeout: float = 0.5) -> Optional[dict]:
    """One-shot status query against a daemon's peer port.  Returns the
    parsed status dict, or None if the daemon is unreachable."""
    import json
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(timeout)
            conn.sendall(wire.frame(wire.u8(OP_STATUS)))
            resp = wire.read_frame(conn)
    except (OSError, ConnectionError, ValueError):
        return None
    if not resp or resp[0] != wire.ST_OK:
        return None
    try:
        return json.loads(wire.Reader(resp[1:]).blob().decode())
    except (ValueError, KeyError):
        return None


def find_leader(peers: list[str], timeout: float = 5.0,
                probe_timeout: float = 0.5) -> Optional[tuple[int, str]]:
    """The FindLeader analog as a framework API (the reference greps
    server logs for the highest "[T<term>] LEADER" banner,
    run.sh:46-68).  Probes the peer table, FOLLOWING leader hints: a
    single reachable replica — leader or not — usually answers in one
    hop with ``leader_addr``.  Returns (slot, control addr) of the
    current leader, or None within ``timeout``.  App clients map the
    slot to the leader's application endpoint (fixed app port per host
    in the reference's deployment, run.sh:72)."""
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        # One pass: direct answers first, else chase the best hint.
        hint = None
        for addr in [a for a in peers if a]:
            st = probe_status(addr, timeout=probe_timeout)
            if st is None:
                continue
            if st.get("is_leader"):
                return st["idx"], addr
            la = st.get("leader_addr")
            if la:
                hint = la
        if hint is not None:
            st = probe_status(hint, timeout=probe_timeout)
            if st is not None and st.get("is_leader"):
                return st["idx"], hint
        time.sleep(0.05)
    return None


def _not_leader(daemon, req_id: Optional[int] = None,
                node=None) -> bytes:
    """NOT_LEADER + the leader's address (not its index: the client's
    peer list may be partial or reordered, so an index is meaningless to
    it).  Empty hint = unknown.  Client ops (clt_write/clt_read) echo
    the request's ``req_id`` after the status byte — the client matches
    it to pair replies under transport-level duplication/reordering;
    the JOIN op (no req_id) omits the echo.  ``node`` selects the
    consensus group whose leader is hinted (different groups may have
    different leaders); None = the primary group."""
    hint = (node.leader_hint if node is not None
            else daemon.leader_hint)
    addr = b""
    if hint is not None and hint < len(daemon.spec.peers):
        addr = daemon.spec.peers[hint].encode()
    echo = b"" if req_id is None else wire.u64(req_id)
    return wire.u8(ST_NOT_LEADER) + echo + wire.blob(addr)


class ApusClient:
    """Cluster client: leader discovery, retries, exactly-once writes.

    ``clt_id`` defaults to a fresh per-INSTANCE id (pid/thread mixed
    with random bits): req_ids are per-client monotone from 1, and the
    server-side dedup caches (clt_id, req_id) replies — two sequential
    instances sharing a clt_id would have the second's early req_ids
    swallowed by the first's cached replies (writes acked but never
    applied).  Callers that pass an explicit clt_id own that
    uniqueness themselves.
    """

    def __init__(self, peers: list[str], clt_id: Optional[int] = None,
                 timeout: float = 5.0, attempt_timeout: float = 2.0,
                 history=None, tracer=None,
                 read_policy: str = "leader", groups: int = 1,
                 wrong_group_refuses: bool = False,
                 retry_budget_rate: float = 10.0,
                 retry_budget_burst: int = 20,
                 breaker_threshold: int = 8,
                 breaker_cooloff: float = 1.0):
        self.peers = [self._parse(p) for p in peers]
        #: Multi-group routing (Multi-Raft): KVS ops hash their key to
        #: one of ``groups`` consensus groups (runtime/router.py) and
        #: ride OP_GROUP-wrapped frames for gid > 0; pipelined bursts
        #: split per group and run CONCURRENT per-group sub-pipelines
        #: over per-(group, peer) connections, merged back in op order.
        #: Per-group leader caches honor per-group NOT_LEADER hints —
        #: different groups may have different leaders.  groups == 1
        #: (default): the router is the identity, nothing is wrapped,
        #: and every frame is byte-identical to the single-group
        #: client.
        self.groups = max(1, groups)
        self._leaders: dict[int, Optional[int]] = {}
        #: Elastic routing: the last shard map learned from a typed
        #: WRONG_GROUP bounce (epoch-versioned; runtime/router.ShardMap).
        #: None until the first bounce — a client of a never-migrated
        #: cluster routes by the pinned hash and pays nothing.
        self.shard = None
        # Cross-group re-dispatch state for pipeline(): ops bounced
        #: WRONG_GROUP leave their sub-pipeline and re-dispatch under
        #: fresh req_ids (see _pipeline_attempt / pipeline).
        self._regroup: list = []
        self._regroup_ids: set = set()
        self._alias: dict[int, int] = {}
        #: Read routing: "leader" (default — every op chases the
        #: leader) or "spread" — GETs rotate across ALL replicas and
        #: are served from follower read leases where live
        #: (linearizable; core/node.py follower_read); a follower
        #: whose lease cannot serve answers NOT_LEADER-with-hint and
        #: the read falls back to the leader.  Writes always chase the
        #: leader regardless.
        self.read_policy = read_policy
        #: WRONG_GROUP answers raise instead of transparently
        #: re-routing to the owner group (the txn plane's driver
        #: client: a 2PC record's group binding is PART OF THE
        #: PROTOCOL — a prepare silently re-routed past a mid-2PC
        #: split would lock keys at a group the coordinator's intent
        #: record never names, and the close could never reach them).
        self.wrong_group_refuses = wrong_group_refuses
        # Desynchronized start: clients constructed together must not
        # herd their spread reads onto the same replica each round.
        self._read_rotor = (secrets.randbits(16) % len(self.peers)
                            if self.peers else 0)
        #: Optional client-side span recorder (apus_tpu.obs.spans.
        #: SpanRecorder): sampled ops get client_send/client_reply
        #: stamps, stitched against the replicas' rings by (clt_id,
        #: req_id) — bench.py --breakdown wires one in.
        self.tracer = tracer
        #: Optional consistency-audit tap (apus_tpu.audit.history.
        #: HistoryRecorder): every op — serial and pipelined — reports
        #: its invoke/response interval and outcome.  Timeouts complete
        #: as "ambiguous" (maybe-applied); a retry chain is ONE interval
        #: because retries reuse the req_id (exactly-once via epdb).
        self.history = history
        self.clt_id = clt_id if clt_id is not None else (
            (os.getpid() << 20) ^ threading.get_ident()
            ^ secrets.randbits(63)) & ((1 << 63) - 1)
        self.timeout = timeout
        #: Per-ATTEMPT wait cap (the overall ``timeout`` still bounds
        #: the op).  A leader that accepts a write but cannot commit it
        #: — isolated from its quorum but still reachable by clients —
        #: holds the connection for the server-side op timeout; without
        #: a per-attempt cap the client burned its whole budget waiting
        #: on that one stuck peer instead of failing over.  Safe to cut
        #: short: the retry reuses the same req_id and the server-side
        #: dedup (epdb) makes it exactly-once wherever it lands.
        self.attempt_timeout = attempt_timeout
        self._req_seq = 0
        # Connections/streams are keyed (gid, target): concurrent
        # per-group sub-pipelines must never share a socket (frame
        # interleaving would corrupt both).  Single-group clients only
        # ever use gid 0 keys.
        self._conns: dict[tuple, socket.socket] = {}
        # One buffered frame stream per connection: ALL reads on a
        # connection go through it (bytes it buffered are invisible to
        # direct socket reads), and a pipelined burst's replies are
        # ingested in ~one recv.
        self._streams: dict[tuple, wire.FrameStream] = {}
        #: client-side fault observability (stale_replies = discarded
        #: duplicated/reordered reply frames; sheds / retry_budget_denied
        #: / breaker_fastfail = the overload cooperation half)
        self.stats: dict[str, int] = {}
        # Overload cooperation (ISSUE 17): per-PEER retry budgets
        # (token bucket — retries against an overloaded peer cannot
        # amplify offered load) and per-peer circuit breakers (a run of
        # consecutive sheds fails fast, typed, for a cooloff window).
        # Seeded RNG so chaos campaigns replay the backoff schedule.
        self._rb_rate = retry_budget_rate
        self._rb_burst = retry_budget_burst
        self._br_threshold = breaker_threshold
        self._br_cooloff = breaker_cooloff
        self._budgets: dict[int, RetryBudget] = {}
        self._breakers: dict[int, CircuitBreaker] = {}
        import random as _random
        self._ovl_rng = _random.Random(self.clt_id & 0xFFFFFFFF)

    @staticmethod
    def _parse(addr: str) -> tuple[str, int]:
        host, port = addr.rsplit(":", 1)
        return host, int(port)

    # -- multi-group plumbing ---------------------------------------------

    @property
    def _leader(self) -> Optional[int]:
        """Group 0's cached leader (single-group compat alias)."""
        return self._leaders.get(0)

    @_leader.setter
    def _leader(self, v: Optional[int]) -> None:
        self._leaders[0] = v

    def _gleader(self, gid: int) -> Optional[int]:
        return self._leaders.get(gid)

    def _set_gleader(self, gid: int, v: Optional[int]) -> None:
        self._leaders[gid] = v

    def group_of(self, key: bytes) -> int:
        """Stable key -> group id (runtime/router.py): the learned
        shard map when one exists (elastic clusters), else the pinned
        hash; 0 when this client is single-group."""
        if self.shard is not None:
            return self.shard.group_of_key(key)
        if self.groups <= 1:
            return 0
        from apus_tpu.runtime.router import group_of_key
        return group_of_key(key, self.groups)

    def _learn_map(self, resp: bytes) -> "tuple[int, int]":
        """Parse a WRONG_GROUP reply (offset 9: status + echoed req_id
        precede): adopt the carried map when it is at least as new as
        ours, and return (owner gid, reply map epoch).  A reply epoch
        BELOW our map's means the answering replica's view lags a flip
        we already know about — the caller must WAIT for it to catch
        up, not re-route by its stale hint (bouncing between a
        flipped src and a lagging dst with no backoff was a
        CPU-saturating ping-pong storm under load)."""
        r = wire.Reader(resp[9:])
        owner = r.u8()
        try:
            from apus_tpu.runtime.router import ShardMap
            m = ShardMap.from_blob(r.blob())
        except (ValueError, IndexError):
            return owner, -1
        if self.shard is None or m.epoch >= self.shard.epoch:
            self.shard = m
            self.groups = max(self.groups, m.n_groups)
        return owner, m.epoch

    @staticmethod
    def _wrap(gid: int, payload: bytes) -> bytes:
        """OP_GROUP envelope for gid > 0; gid 0 frames stay bare
        (byte-identical to the single-group protocol)."""
        if gid == 0:
            return payload
        return wire.u8(wire.OP_GROUP) + wire.u8(gid) + payload

    def close(self) -> None:
        for c in self._conns.values():
            try:
                c.close()
            except OSError:
                pass
        self._conns.clear()
        self._streams.clear()

    def __enter__(self) -> "ApusClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- raw ops ----------------------------------------------------------

    def write(self, data: bytes) -> bytes:
        self._req_seq += 1
        return self._op(OP_CLT_WRITE, self._req_seq, data)

    def read(self, data: bytes) -> bytes:
        self._req_seq += 1
        return self._op(OP_CLT_READ, self._req_seq, data)

    def _spread_target(self) -> Optional[int]:
        """Next read target under read_policy='spread' (round-robin
        over the known peer table)."""
        if self.read_policy != "spread" or not self.peers:
            return None
        self._read_rotor = (self._read_rotor + 1) % len(self.peers)
        return self._read_rotor

    # -- pipelined ops ----------------------------------------------------

    #: default in-flight window for pipeline() — matches the device
    #: engine's 64-entry slot window, so one full client window can ride
    #: one replicated commit round.
    pipeline_window = 64

    def pipeline(self, ops, window: Optional[int] = None) -> list[bytes]:
        """Pipelined batch: write up to ``window`` framed requests ahead
        of reading replies (one vectored flush per sub-window), pairing
        replies by the echoed req_id — out-of-order and duplicated
        frames are discarded/reordered exactly as the single-op path.
        ``ops`` is a sequence of ``(op, data)`` or ``(op, data, gid)``
        with op in {OP_CLT_WRITE, OP_CLT_READ} (the 3-tuple form routes
        to consensus group ``gid``; the KVS helpers below derive gid
        from the key).  Returns the reply bodies in op order, with
        redis-pipeline program-order semantics WITHIN a group: a read
        observes every same-group write earlier in the same pipeline
        call (the server floors each read's wait index past the burst's
        earlier writes; it may additionally observe later writes that
        applied in the same commit window).  Ops routed to different
        groups interleave freely — each group is an independent log,
        so a cross-group write-then-read pair in ONE burst carries no
        ordering promise (tests/test_txn.py pins this at the wire);
        callers needing cross-group read-your-write or atomic
        visibility use :meth:`txn`, the stated cross-group
        alternative.
        A multi-group burst splits per group and the sub-pipelines run
        CONCURRENTLY (each on its own (group, peer) connections),
        replies merged back in op order.  Failover-safe: unresolved
        ops are resent to the next target with the SAME req_ids, and
        the server-side per-group dedup (core.epdb) keeps retried
        writes exactly-once."""
        window = window or self.pipeline_window
        items = []
        for entry in ops:
            if len(entry) == 3:
                op, data, gid = entry
            else:
                op, data = entry
                gid = 0
            self._req_seq += 1
            items.append((op, self._req_seq, data, gid))
            if self.history is not None:
                self.history.invoke(self.clt_id, self._req_seq, op, data)
            if self.tracer is not None \
                    and self.tracer.sampled(self._req_seq):
                self.tracer.stamp(self.clt_id, self._req_seq,
                                  "client_send")
        results: dict[int, bytes] = {}
        deadline = time.monotonic() + self.timeout
        by_gid: dict[int, list] = {}
        for it in items:
            by_gid.setdefault(it[3], []).append(it)
        # Fresh cross-group re-dispatch state per pipeline call
        # (ops bounced WRONG_GROUP re-dispatch below).
        self._regroup = []
        self._regroup_ids = set()
        self._alias = {}
        try:
            self._run_group_pipelines(by_gid, results, deadline, window)
            # Elastic re-dispatch rounds: ops bounced WRONG_GROUP get
            # FRESH req_ids at their owner group (the refusal was
            # deterministic — they never applied at the bouncer), with
            # results and history keyed back to the original op.
            for _round in range(6):
                regroup, self._regroup = self._regroup, []
                if not regroup:
                    break
                by_gid2: dict[int, list] = {}
                for (op, rid, data, _g), owner in regroup:
                    orig = self._alias.get(rid, rid)
                    self._req_seq += 1
                    nrid = self._req_seq
                    self._alias[nrid] = orig
                    by_gid2.setdefault(owner, []).append(
                        (op, nrid, data, owner))
                self._run_group_pipelines(by_gid2, results, deadline,
                                          window)
            missing = [rid for _op, rid, _d, _g in items
                       if rid not in results]
            if missing:
                raise TimeoutError(
                    f"{len(missing)} of {len(items)} pipelined ops "
                    f"unresolved after cross-group re-dispatch")
        except BaseException:
            # Unresolved ops are ambiguous: a retry MAY already have
            # landed (the reply was simply never read).
            if self.history is not None:
                for _op, rid, _d, _g in items:
                    if rid not in results:
                        self.history.complete(self.clt_id, rid,
                                              "ambiguous")
            raise
        return [results[req_id] for _op, req_id, _d, _g in items]

    def _run_group_pipelines(self, by_gid: dict, results: dict,
                             deadline: float, window: int) -> None:
        """Drive one round of per-group sub-pipelines (concurrent when
        more than one group has ops; connections are keyed
        (gid, target), so threads never share a socket even when two
        groups' leaders are the same daemon)."""
        if not by_gid:
            return
        if len(by_gid) == 1:
            gid, sub = next(iter(by_gid.items()))
            self._pipeline_group(gid, sub, results, deadline, window)
            return
        errs: list[BaseException] = []

        def run(gid, sub):
            try:
                self._pipeline_group(gid, sub, results, deadline,
                                     window)
            except BaseException as e:   # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=run, args=(g, s),
                                    daemon=True)
                   for g, s in by_gid.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def _pipeline_group(self, gid: int, items: list,
                        results: dict, deadline: float,
                        window: int) -> None:
        """Drive one group's sub-pipeline to completion (chasing that
        GROUP's leader via its own NOT_LEADER hints)."""
        # Pure-read bursts under read_policy='spread' rotate across
        # replicas (served from follower read leases); a NOT_LEADER
        # bounce falls back to the hinted leader for the remainder.
        spread = (self.read_policy == "spread"
                  and all(op == OP_CLT_READ for op, _r, _d, _g in items))
        target = self._spread_target() if spread else self._gleader(gid)
        if target is None:
            target = self._gleader(gid)
        pending = items
        ovl_attempt = 0
        while pending:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"{len(pending)} of {len(items)} pipelined ops "
                    f"(group {gid}) not served in {self.timeout}s")
            if target is None:
                target = self._probe_any(deadline, gid)
                if target is None:
                    continue
            outcome, hint = self._pipeline_attempt(
                target, pending, results, deadline, window,
                learn_leader=not spread, gid=gid)
            pending = [it for it in pending if it[1] not in results
                       and it[1] not in self._regroup_ids]
            if outcome == "overload":
                # Sheds in the burst: budgeted, jittered backoff, then
                # retry the unresolved tail at the SAME target under
                # the SAME req_ids; an exhausted budget surfaces typed.
                ovl_attempt += 1
                if not self._shed_retry_wait(target, ovl_attempt,
                                             hint, deadline):
                    raise Overloaded(
                        f"{len(pending)} pipelined ops (group {gid}) "
                        f"shed by peer {target} "
                        f"(retry budget exhausted)", hint)
            elif outcome == "migrating":
                time.sleep(0.02)         # freeze window; same target
            elif outcome == "hint":
                target = self._peer_index(hint) if hint \
                    else (self._gleader(gid) if spread
                          and self._gleader(gid) is not None
                          else self._next(target, gid))
                time.sleep(0.01)
            elif outcome != "ok":
                target = ((target + 1) % len(self.peers)
                          if spread else self._next(target, gid))

    def pipeline_writes(self, datas) -> list[bytes]:
        return self.pipeline([(OP_CLT_WRITE, d) for d in datas])

    def pipeline_reads(self, datas) -> list[bytes]:
        return self.pipeline([(OP_CLT_READ, d) for d in datas])

    def pipeline_puts(self, pairs) -> list[bytes]:
        from apus_tpu.models.kvs import encode_put
        return self.pipeline(
            [(OP_CLT_WRITE, encode_put(k, v), self.group_of(k))
             for k, v in pairs])

    def pipeline_gets(self, keys) -> list[bytes]:
        from apus_tpu.models.kvs import encode_get
        return self.pipeline(
            [(OP_CLT_READ, encode_get(k), self.group_of(k))
             for k in keys])

    def _pipeline_attempt(self, target: int, items: list, results: dict,
                          deadline: float, window: int,
                          learn_leader: bool = True, gid: int = 0):
        """One pipelined exchange against ``target``.  Returns
        ("ok", None) when every item resolved, ("hint", addr_or_None)
        on NOT_LEADER, ("rotate", None) on a peer-side commit timeout,
        ("conn", None) on connection trouble — unresolved items stay
        out of ``results`` and are retried by the caller."""
        conn = self._connect(target, deadline, gid)
        if conn is None:
            return "conn", None
        queue = list(items)
        inflight: dict[int, tuple] = {}
        migrating = False
        shed_ms = None
        any_ok = False
        try:
            while queue or inflight:
                if queue and len(inflight) < window:
                    burst = queue[:window - len(inflight)]
                    del queue[:len(burst)]
                    wire.send_frames(conn, [
                        self._wrap(gid, wire.u8(op) + wire.u64(rid)
                                   + wire.u64(self.clt_id)
                                   + wire.blob(data))
                        for op, rid, data, _g in burst])
                    for it in burst:
                        inflight[it[1]] = it
                conn.settimeout(max(0.05, min(
                    deadline - time.monotonic(), self.attempt_timeout)))
                resp = self._streams[(gid, target)].next_frame()
                if resp is None:
                    raise ConnectionError("peer closed")
                if len(resp) < 9:
                    raise ValueError("short reply frame")
                rid = wire.Reader(resp[1:9]).u64()
                if rid not in inflight:
                    # Duplicated/reordered stale frame (or the tail of
                    # an aborted earlier exchange on this connection).
                    self.stats["stale_replies"] = \
                        self.stats.get("stale_replies", 0) + 1
                    continue
                st = resp[0]
                if st == wire.ST_OK:
                    if learn_leader:
                        self._set_gleader(gid, target)
                    any_ok = True
                    val = wire.Reader(resp[9:]).blob()
                    # Cross-group re-dispatches resolve under their
                    # ORIGINAL req_id too (the caller's op order and
                    # the history interval are keyed by it).
                    orig = self._alias.get(rid, rid)
                    results[rid] = val
                    results[orig] = val
                    del inflight[rid]
                    if self.history is not None:
                        self.history.complete(self.clt_id, orig, "ok",
                                              val)
                    if self.tracer is not None and orig == rid \
                            and self.tracer.sampled(rid):
                        self.tracer.stamp(self.clt_id, rid,
                                          "client_reply")
                        self.tracer.finish(self.clt_id, rid)
                elif st == ST_OVERLOAD:
                    # Typed shed: leave unresolved (deterministic —
                    # nothing applied; the caller's budgeted backoff
                    # retries it under the SAME req_id).
                    shed_ms = self._on_shed(target, resp)
                    del inflight[rid]
                elif st == ST_MIGRATING:
                    # Bucket frozen mid-migration: leave unresolved;
                    # the caller retries this target after a short
                    # backoff (the flip resolves it).
                    del inflight[rid]
                    migrating = True
                elif st == ST_WRONG_GROUP:
                    owner, repoch = self._learn_map(resp)
                    if self.shard is not None \
                            and repoch < self.shard.epoch:
                        # Lagging replica (see _op_raw): retry here
                        # after the caller's backoff, same req_id.
                        del inflight[rid]
                        migrating = True
                    else:
                        # Owned by another group: hand the op to the
                        # pipeline-level re-dispatcher (fresh req_id
                        # at the owner; see pipeline()).
                        it = inflight.pop(rid)
                        self._regroup_ids.add(rid)
                        self._regroup.append((it, owner))
                elif st == ST_NOT_LEADER:
                    hint = wire.Reader(resp[9:]).blob().decode() \
                        if len(resp) > 9 else ""
                    return "hint", (hint or None)
                elif st == ST_TIMEOUT:
                    # The peer led but could not commit in its window:
                    # rotate (same rationale as the single-op path).
                    return "rotate", None
                else:
                    raise RuntimeError(f"server error (status {st})")
            if any_ok:
                # The peer is (partially) serving: reset the breaker's
                # consecutive-shed count — it must only trip on a peer
                # shedding EVERYTHING.
                self._breaker(target).record_ok()
            if shed_ms is not None:
                return "overload", shed_ms
            return ("migrating" if migrating else "ok"), None
        except (OSError, ConnectionError, ValueError):
            self._drop(target, gid)
            return "conn", None

    # -- kvs convenience (the DARE client's PUT/GET/RM, dare_kvs_sm.c) ----

    def put(self, key: bytes, value: bytes) -> bytes:
        from apus_tpu.models.kvs import encode_put
        self._req_seq += 1
        return self._op(OP_CLT_WRITE, self._req_seq,
                        encode_put(key, value), gid=self.group_of(key))

    def get(self, key: bytes) -> bytes:
        from apus_tpu.models.kvs import encode_get
        self._req_seq += 1
        return self._op(OP_CLT_READ, self._req_seq, encode_get(key),
                        gid=self.group_of(key))

    def delete(self, key: bytes) -> bytes:
        from apus_tpu.models.kvs import encode_delete
        self._req_seq += 1
        return self._op(OP_CLT_WRITE, self._req_seq,
                        encode_delete(key), gid=self.group_of(key))

    # -- typed replicated-data-type ops (PR 12) ---------------------------

    def incr(self, key: bytes, delta: int = 1) -> int:
        """Counter add (redis INCR/DECR/INCRBY); returns the NEW
        value.  Rides the ordinary write path — typed state is an
        ordinary store value in a canonical encoding."""
        from apus_tpu.models.kvs import encode_incr
        self._req_seq += 1
        r = self._op(OP_CLT_WRITE, self._req_seq,
                     encode_incr(key, delta), gid=self.group_of(key))
        return int(r)

    def getset(self, key: bytes, value: bytes) -> bytes:
        """Set ``value``, return the OLD value (b"" if absent)."""
        from apus_tpu.models.kvs import encode_getset
        self._req_seq += 1
        return self._op(OP_CLT_WRITE, self._req_seq,
                        encode_getset(key, value),
                        gid=self.group_of(key))

    def sadd(self, key: bytes, member: bytes) -> bool:
        from apus_tpu.models.kvs import encode_sadd
        self._req_seq += 1
        return self._op(OP_CLT_WRITE, self._req_seq,
                        encode_sadd(key, member),
                        gid=self.group_of(key)) == b"1"

    def srem(self, key: bytes, member: bytes) -> bool:
        from apus_tpu.models.kvs import encode_srem
        self._req_seq += 1
        return self._op(OP_CLT_WRITE, self._req_seq,
                        encode_srem(key, member),
                        gid=self.group_of(key)) == b"1"

    def smembers(self, key: bytes) -> "set[bytes]":
        from apus_tpu.models.kvs import encode_smembers, set_decode
        self._req_seq += 1
        return set_decode(self._op(OP_CLT_READ, self._req_seq,
                                   encode_smembers(key),
                                   gid=self.group_of(key)))

    # -- transactions (PR 12; runtime/txn.py) ------------------------------

    @staticmethod
    def _encode_sub(sub) -> bytes:
        from apus_tpu.models import kvs
        op = sub[0]
        key = sub[1]
        arg = sub[2] if len(sub) > 2 else None
        if op == "put":
            return kvs.encode_put(key, arg)
        if op == "get":
            return kvs.encode_get(key)
        if op == "delete":
            return kvs.encode_delete(key)
        if op == "incr":
            return kvs.encode_incr(key, arg if arg is not None else 1)
        if op == "getset":
            return kvs.encode_getset(key, arg)
        if op == "sadd":
            return kvs.encode_sadd(key, arg)
        if op == "srem":
            return kvs.encode_srem(key, arg)
        if op == "smembers":
            return kvs.encode_smembers(key)
        raise ValueError(f"unknown txn sub-op {op!r}")

    def txn(self, subs) -> "list[bytes]":
        """Atomic multi-key transaction: ``subs`` is a list of
        ``(op, key[, arg])`` with op in {"put", "get", "delete",
        "incr", "getset", "sadd", "srem", "smembers"}.  Returns the
        per-sub reply bytes in order.

        Atomic visibility ACROSS groups: keys hashing to one group
        commit as ONE log entry; keys spanning groups ride the
        replicated 2PC (runtime/txn.py) — this is the stated
        cross-group alternative to pipelined read-your-write, which
        remains a WITHIN-group contract.  Reads observe earlier
        same-txn writes.  Exactly-once: the decision record carries
        this client's (clt_id, req_id), deduped by the coordinator
        group's endpoint DB; deterministic aborts (lock conflicts, a
        split/merge racing the 2PC) retry under a FRESH req_id."""
        from apus_tpu.models.kvs import unpack_replies
        from apus_tpu.runtime.txn import (OP_TXN, ST_TXN_ABORTED,
                                          encode_txn_subs)
        cmds = [self._encode_sub(s) for s in subs]
        blob = encode_txn_subs(cmds)
        self._req_seq += 1
        orig = req_id = self._req_seq
        if self.history is not None:
            self.history.invoke_txn(self.clt_id, orig, cmds)
        # First target: the cached leader of the expected coordinator
        # group (min participant gid under OUR map; the server replans
        # under its own — NOT_LEADER hints re-aim us).
        gids = {self.group_of(s[1]) for s in subs}
        target = self._gleader(min(gids)) if gids else None
        deadline = time.monotonic() + self.timeout
        rng_backoff = 0.01
        try:
            while time.monotonic() < deadline:
                if target is None:
                    target = self._probe_any(deadline)
                    if target is None:
                        continue
                payload = (wire.u8(OP_TXN) + wire.u64(req_id)
                           + wire.u64(self.clt_id) + wire.blob(blob))
                resp = self._roundtrip(target, payload, deadline,
                                       req_id)
                if resp is None:
                    target = self._next(target)
                    continue
                st = resp[0]
                if st == wire.ST_OK:
                    reply = wire.Reader(resp[9:]).blob()
                    rets = [r for _p, r in
                            sorted(unpack_replies(reply))]
                    if self.history is not None:
                        self.history.complete_txn(self.clt_id, orig,
                                                  "ok", rets)
                    return rets
                if st == ST_NOT_LEADER:
                    hint = wire.Reader(resp[9:]).blob().decode() \
                        if len(resp) > 9 else ""
                    target = self._peer_index(hint) if hint \
                        else self._next(target)
                    time.sleep(0.01)
                    continue
                if st == ST_TXN_ABORTED or st == ST_WRONG_GROUP \
                        or st == ST_MIGRATING:
                    # Deterministic refusal — nothing applied
                    # anywhere; retry the WHOLE transaction under a
                    # fresh req_id (jittered: lock-conflict livelock
                    # is broken by desynchronized retries).
                    if st == ST_WRONG_GROUP:
                        self._learn_map(resp)
                    self._req_seq += 1
                    req_id = self._req_seq
                    time.sleep(rng_backoff
                               * (0.5 + secrets.randbits(8) / 256.0))
                    rng_backoff = min(0.16, rng_backoff * 2)
                    continue
                if st == ST_TIMEOUT:
                    target = self._next(target)
                    continue
                if self.history is not None:
                    self.history.complete_txn(self.clt_id, orig,
                                              "error")
                raise RuntimeError(f"txn refused (status {st})")
        except BaseException:
            if self.history is not None:
                self.history.complete_txn(self.clt_id, orig,
                                          "ambiguous")
            raise
        if self.history is not None:
            self.history.complete_txn(self.clt_id, orig, "ambiguous")
        raise TimeoutError(
            f"txn {orig} not decided in {self.timeout}s")

    # -- internals --------------------------------------------------------

    def _op(self, op: int, req_id: int, data: bytes,
            gid: int = 0) -> bytes:
        """One client op with audit capture: the whole retry chain is
        one recorded interval; timeouts are ambiguous (maybe-applied),
        server errors are ambiguous-for-writes."""
        if self.tracer is not None and self.tracer.sampled(req_id):
            self.tracer.stamp(self.clt_id, req_id, "client_send")
            try:
                reply = self._op_history(op, req_id, data, gid)
            except BaseException:
                self.tracer.finish(self.clt_id, req_id)
                raise
            self.tracer.stamp(self.clt_id, req_id, "client_reply")
            self.tracer.finish(self.clt_id, req_id)
            return reply
        return self._op_history(op, req_id, data, gid)

    def _op_history(self, op: int, req_id: int, data: bytes,
                    gid: int = 0) -> bytes:
        if self.history is None:
            return self._op_raw(op, req_id, data, gid)
        self.history.invoke(self.clt_id, req_id, op, data)
        try:
            reply = self._op_raw(op, req_id, data, gid)
        except TimeoutError:
            self.history.complete(self.clt_id, req_id, "ambiguous")
            raise
        except RuntimeError:
            self.history.complete(self.clt_id, req_id, "error")
            raise
        self.history.complete(self.clt_id, req_id, "ok", reply)
        return reply

    def _op_raw(self, op: int, req_id: int, data: bytes,
                gid: int = 0) -> bytes:
        payload = self._wrap(gid, wire.u8(op) + wire.u64(req_id)
                             + wire.u64(self.clt_id) + wire.blob(data))
        deadline = time.monotonic() + self.timeout
        # Spread reads rotate across replicas (follower read leases);
        # their failovers must not clobber the cached leader the write
        # path relies on, so they rotate locally instead of _next().
        spread = op == OP_CLT_READ and self.read_policy == "spread"
        target = self._spread_target() if spread else self._gleader(gid)
        if target is None:
            target = self._gleader(gid)
        ovl_attempt = 0
        fastfails = 0
        while time.monotonic() < deadline:
            if target is None:
                target = self._probe_any(deadline, gid)
                if target is None:
                    continue
            br = self._breaker(target)
            if not br.allow():
                # Breaker open for this peer: fail fast off the wire.
                # Rotate WITHOUT clearing the cached leader (the peer
                # is overloaded, not deposed); if every peer's breaker
                # is open, surface the typed refusal instead of
                # spinning until the deadline.
                self.stats["breaker_fastfail"] = \
                    self.stats.get("breaker_fastfail", 0) + 1
                fastfails += 1
                if fastfails >= max(4, 2 * len(self.peers)):
                    raise Overloaded(
                        f"request {req_id}: circuit open to all peers")
                target = (target + 1) % len(self.peers)
                time.sleep(0.005)
                continue
            resp = self._roundtrip(target, payload, deadline, req_id,
                                   gid)
            if resp is None:
                target = ((target + 1) % len(self.peers) if spread
                          else self._next(target, gid))
                continue
            st = resp[0]
            # Replies echo req_id after the status byte (reply pairing
            # under duplication/reordering; _roundtrip already matched
            # it) — the body starts at offset 9.
            if st == wire.ST_OK:
                if not spread:
                    self._set_gleader(gid, target)
                br.record_ok()
                return wire.Reader(resp[9:]).blob()
            if st == ST_OVERLOAD:
                # Typed shed: deterministic refusal, nothing applied —
                # retry the SAME target under the SAME req_id after a
                # budgeted, jittered backoff honoring the server's
                # retry-after hint.  An exhausted budget raises typed
                # (Overloaded) instead of amplifying offered load.
                retry_ms = self._on_shed(target, resp)
                ovl_attempt += 1
                if not self._shed_retry_wait(target, ovl_attempt,
                                             retry_ms, deadline):
                    raise Overloaded(
                        f"request {req_id} shed by peer {target} "
                        f"(retry budget exhausted)", retry_ms)
                continue
            if st == ST_NOT_LEADER:
                hint = wire.Reader(resp[9:]).blob().decode() if \
                    len(resp) > 9 else ""
                if spread:
                    # Lease cold/lapsed at that follower: fall back to
                    # the leader for THIS read, keep the rotor for the
                    # next one.
                    target = (self._peer_index(hint) if hint
                              else self._gleader(gid)
                              if self._gleader(gid) is not None
                              else (target + 1) % len(self.peers))
                else:
                    target = self._peer_index(hint) if hint \
                        else self._next(target, gid)
                time.sleep(0.01)
                continue
            if st == ST_TIMEOUT:
                # The peer led but could not commit within its window
                # (quorum loss / partition): ROTATE instead of retrying
                # the same stuck leader until our own deadline — the
                # same req_id is exactly-once wherever it lands, and a
                # healthy majority may be one hop away.
                target = self._next(target, gid)
                continue
            if st == ST_MIGRATING:
                # Bucket frozen mid-migration: the flip resolves this
                # to OK or WRONG_GROUP within the migration's (short)
                # freeze window.  Same target, small backoff.
                time.sleep(0.02)
                continue
            if st == ST_WRONG_GROUP:
                if self.wrong_group_refuses:
                    raise RuntimeError("wrong_group")
                owner, repoch = self._learn_map(resp)
                if self.shard is not None \
                        and repoch < self.shard.epoch:
                    # The answering replica's map LAGS ours: its view
                    # of this flip hasn't applied yet — wait it out on
                    # the same group instead of chasing the stale hint
                    # (the src/dst ping-pong storm).
                    time.sleep(0.02)
                    continue
                # The bucket is owned by another group (the reply
                # carried the map).  The refusal is deterministic — the
                # op never applied here — so re-route under a FRESH
                # req_id: per-(client, group) req_id streams stay
                # monotone on both sides and the owner executes it
                # exactly once.
                gid = owner
                self._req_seq += 1
                req_id = self._req_seq
                payload = self._wrap(gid, wire.u8(op) + wire.u64(req_id)
                                     + wire.u64(self.clt_id)
                                     + wire.blob(data))
                target = self._gleader(gid)
                time.sleep(0.01)
                continue
            raise RuntimeError(f"server error (status {st})")
        raise TimeoutError(f"request {req_id} not served in {self.timeout}s")

    def _budget(self, target: int) -> RetryBudget:
        b = self._budgets.get(target)
        if b is None:
            b = self._budgets[target] = RetryBudget(self._rb_rate,
                                                    self._rb_burst)
        return b

    def _breaker(self, target: int) -> CircuitBreaker:
        b = self._breakers.get(target)
        if b is None:
            b = self._breakers[target] = CircuitBreaker(
                self._br_threshold, self._br_cooloff)
        return b

    def breaker_view(self) -> dict:
        """Per-peer breaker/budget snapshot (failure dumps attach this
        beside the server-side overload view)."""
        return {t: {**self._breakers[t].snapshot(),
                    "budget_tokens": round(self._budget(t).tokens, 1),
                    "budget_denied": self._budget(t).denied}
                for t in sorted(self._breakers)}

    def _on_shed(self, target: int, resp: bytes) -> int:
        """Account one typed shed from ``target``; returns the
        server's retry-after hint (ms)."""
        self.stats["sheds"] = self.stats.get("sheds", 0) + 1
        self._breaker(target).record_shed()
        return parse_retry_after(resp)

    def _shed_retry_wait(self, target: int, attempt: int,
                         retry_ms: int, deadline: float) -> bool:
        """Spend one retry-budget token and sleep the jittered backoff;
        False (caller raises Overloaded) when the budget is empty or
        the deadline cannot absorb the wait — the amplification
        brake."""
        if not self._budget(target).try_spend():
            self.stats["retry_budget_denied"] = \
                self.stats.get("retry_budget_denied", 0) + 1
            return False
        wait = backoff_s(attempt, retry_ms, self._ovl_rng.random())
        if time.monotonic() + wait >= deadline:
            return False
        time.sleep(wait)
        return True

    def _peer_index(self, addr: str) -> int:
        """Index of ``addr`` in our peer list, learning it if new."""
        pa = self._parse(addr)
        for i, p in enumerate(self.peers):
            if p == pa:
                return i
        self.peers.append(pa)
        return len(self.peers) - 1

    def _next(self, current: Optional[int], gid: int = 0) -> int:
        self._set_gleader(gid, None)
        if current is None:
            return 0
        return (current + 1) % len(self.peers)

    def _probe_any(self, deadline: float, gid: int = 0) -> Optional[int]:
        for i in range(len(self.peers)):
            if self._connect(i, deadline, gid) is not None:
                return i
        time.sleep(0.05)
        return None

    def _connect(self, target: int, deadline: float,
                 gid: int = 0) -> Optional[socket.socket]:
        conn = self._conns.get((gid, target))
        if conn is not None:
            return conn
        try:
            conn = socket.create_connection(
                self.peers[target],
                timeout=max(0.05, min(1.0, deadline - time.monotonic())))
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns[(gid, target)] = conn
            self._streams[(gid, target)] = wire.FrameStream(conn)
            return conn
        except OSError:
            return None

    def _roundtrip(self, target: int, payload: bytes, deadline: float,
                   req_id: int, gid: int = 0) -> Optional[bytes]:
        """One request/response exchange, paired by the reply's echoed
        req_id: frames whose echo doesn't match are STALE — duplicated
        or reordered replies to an earlier request on this (reused)
        connection — and are discarded, not misread as this request's
        answer.  Pre-fix a duplicated reply desynchronized the
        connection's request/reply pairing for every later op."""
        conn = self._connect(target, deadline, gid)
        if conn is None:
            return None
        try:
            conn.settimeout(max(0.05, min(deadline - time.monotonic(),
                                          self.attempt_timeout)))
            conn.sendall(wire.frame(payload))
            stream = self._streams[(gid, target)]
            while True:
                resp = stream.next_frame()
                if resp is None:
                    raise ConnectionError("peer closed")
                if len(resp) >= 9 and \
                        wire.Reader(resp[1:9]).u64() != req_id:
                    self.stats["stale_replies"] = \
                        self.stats.get("stale_replies", 0) + 1
                    continue
                return resp
        except (OSError, ConnectionError, ValueError):
            self._drop(target, gid)
            return None

    def _drop(self, target: int, gid: int = 0) -> None:
        self._streams.pop((gid, target), None)
        conn = self._conns.pop((gid, target), None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
