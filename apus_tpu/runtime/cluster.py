"""LocalCluster: N in-process replica daemons on loopback.

The live-network analog of the simulator's Cluster (apus_tpu.parallel.sim)
and of the reference's ssh-launched groups (benchmarks/run.sh:23-31): it
reserves loopback ports, builds one shared ClusterSpec (nodes.cfg
analog), and runs each replica's daemon with real TCP between them.
Used by the end-to-end tests and the benchmark harness.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from apus_tpu.models.kvs import KvsStateMachine
from apus_tpu.models.sm import StateMachine
from apus_tpu.parallel.net import PeerServer
from apus_tpu.runtime.daemon import ReplicaDaemon
from apus_tpu.runtime.membership import request_join
from apus_tpu.utils.config import ClusterSpec


class LocalCluster:
    def __init__(self, n: int, spec: Optional[ClusterSpec] = None,
                 sm_factory: Callable[[], StateMachine] = KvsStateMachine,
                 daemon_cls=ReplicaDaemon, seed: int = 0,
                 device_plane: bool = False, device_batch: int = 16,
                 device_devices=None, groups: int = 1,
                 group_major: bool = False, **daemon_kwargs):
        self.n = n
        self.sm_factory = sm_factory
        self.daemon_cls = daemon_cls
        self.seed = seed
        self.daemon_kwargs = daemon_kwargs
        # Reserve ports first so every daemon knows all peers up front.
        socks = [PeerServer.reserve() for _ in range(n)]
        peers = [f"{s.getsockname()[0]}:{s.getsockname()[1]}" for s in socks]
        base = spec or ClusterSpec(
            hb_period=0.005, hb_timeout=0.030,
            elect_low=0.050, elect_high=0.150)
        groups = max(groups, getattr(base, "groups", 1))
        self.spec = dataclasses.replace(base, group_size=n, peers=peers,
                                        groups=groups)
        self.groups = groups
        # Shared device-plane engine (one mesh per process, like one TPU
        # pod slice per host); each daemon's driver binds its replica to
        # a shard.  Replication through the jitted commit step, host TCP
        # as control plane + catch-up (runtime.device_plane).  With
        # groups > 1 the GROUP-MAJOR engine (runtime.group_plane) runs
        # instead: many groups' windows per dispatch.
        self.device_runner = None
        if device_plane and (groups > 1 or group_major):
            # group_major=True forces the group-major engine even at
            # groups == 1 — the bench's apples-to-apples ladder floor.
            from apus_tpu.runtime.group_plane import GroupDeviceRunner
            self.device_runner = GroupDeviceRunner(
                n_groups=groups, n_replicas=n,
                slot_bytes=self.spec.slot_bytes, batch=device_batch,
                devices=device_devices)
            self.daemon_kwargs = dict(self.daemon_kwargs,
                                      device_runner=self.device_runner)
        elif device_plane:
            from apus_tpu.runtime.device_plane import DeviceCommitRunner
            self.device_runner = DeviceCommitRunner(
                n_replicas=n, n_slots=self.spec.n_slots,
                slot_bytes=self.spec.slot_bytes, batch=device_batch,
                devices=device_devices)
            self.daemon_kwargs = dict(self.daemon_kwargs,
                                      device_runner=self.device_runner)
        self.daemons: list[Optional[ReplicaDaemon]] = [
            daemon_cls(i, self.spec, sm=sm_factory(), listen_sock=socks[i],
                       seed=seed, **self.daemon_kwargs)
            for i in range(n)
        ]

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        for d in self.daemons:
            if d is not None:
                d.start()

    def stop(self) -> None:
        for d in self.daemons:
            if d is not None:
                d.stop()

    def __enter__(self) -> "LocalCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- queries ----------------------------------------------------------

    def live(self) -> list[ReplicaDaemon]:
        return [d for d in self.daemons if d is not None]

    def leader(self) -> Optional[ReplicaDaemon]:
        leaders = [d for d in self.live() if d.is_leader]
        if not leaders:
            return None
        return max(leaders, key=lambda d: d.term)

    def group_leader(self, gid: int) -> Optional[ReplicaDaemon]:
        """The daemon currently leading consensus group ``gid`` (may
        differ per group), or None."""
        best = None
        for d in self.live():
            node = d.group_node(gid)
            if node is not None and node.is_leader:
                if best is None or node.current_term > \
                        best.group_node(gid).current_term:
                    best = d
        return best

    def wait_for_group_leaders(self, timeout: float = 20.0) -> dict:
        """Block until EVERY group has exactly one live leader; returns
        {gid: daemon}."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            out = {}
            for gid in range(self.groups):
                leaders = []
                for d in self.live():
                    node = d.group_node(gid)
                    with d.lock:
                        if node is not None and node.is_leader:
                            leaders.append(d)
                if len(leaders) == 1:
                    out[gid] = leaders[0]
            if len(out) == self.groups:
                return out
            time.sleep(0.005)
        raise AssertionError(
            f"not all {self.groups} groups elected a stable leader "
            f"within {timeout}s")

    def wait_for_leader(self, timeout: float = 15.0) -> ReplicaDaemon:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # A stable leader: exactly one live daemon claims leadership.
            # Checked under each daemon's lock so that when this returns,
            # the leader's current tick — including its bridge's shm role
            # mirror (runtime/bridge.py) — has fully completed.
            leaders = []
            for d in self.live():
                with d.lock:
                    if d.is_leader:
                        leaders.append(d)
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.005)
        raise AssertionError("no stable leader within timeout")

    # -- client ops -------------------------------------------------------

    _seq = 0

    def submit(self, data: bytes, timeout: float = 10.0,
               clt_id: int = 0):
        """Submit to the current leader, retrying across elections."""
        LocalCluster._seq += 1
        req_id = LocalCluster._seq
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leader = self.wait_for_leader(deadline - time.monotonic())
            pr = leader.submit(req_id, clt_id, data)
            if pr is not None and leader.wait_committed(
                    pr, min(2.0, deadline - time.monotonic())):
                return leader, pr
        raise AssertionError(f"request not committed within {timeout}s")

    # -- fault injection --------------------------------------------------

    def kill(self, idx: int) -> None:
        d = self.daemons[idx]
        if d is not None:
            d.stop()
            self.daemons[idx] = None

    def restart(self, idx: int) -> "ReplicaDaemon":
        """Restart a killed replica at its original endpoint (full
        recovery path: durable-store replay + catch-up from peers).

        If a live leader's membership EXCLUDES this slot — the failure
        detector auto-removed it while it was dead — the slot is first
        re-admitted through the join protocol, mirroring the daemon
        CLI's rejoin-on-exclusion (runtime.daemon main loop, which
        re-execs itself in --join mode): without this, a removed thread
        replica would restart into a group that never contacts it."""
        assert self.daemons[idx] is None, "kill before restart"
        # The exclusion question needs a stable leader; wait briefly for
        # one.  If none appears, proceed WITHOUT the rejoin: either the
        # group still lists us (normal recovery works) or it cannot
        # elect until this replica returns — and a removed replica
        # cannot help elect anyway, so blocking the restart would only
        # deepen the outage.
        rejoin_cid = None
        try:
            ld = self.wait_for_leader(timeout=5.0)
        except AssertionError:
            ld = None
        if ld is not None:
            with ld.lock:
                excluded = (ld.node.is_leader
                            and not ld.node.cid.contains(idx))
            if excluded:
                addr = self.spec.peers[idx]
                # Slot affinity: admitted at this exact slot or refused
                # (identity is keyed by slot).
                slot, rejoin_cid, _peers = request_join(
                    [p for i, p in enumerate(self.spec.peers)
                     if p and i != idx], addr, want_slot=idx)
                assert slot == idx, (slot, idx)
        kwargs = dict(self.daemon_kwargs)
        if rejoin_cid is not None:
            # Seed the re-admitted member with the configuration the
            # join returned (parity with add_replica and the daemon
            # CLI's --join path) instead of a stale epoch-0 full set.
            kwargs["cid"] = rejoin_cid
            if self.groups > 1:
                # Re-admit into every extra group too (idempotent for
                # groups that still list the slot); the per-group
                # exclusion watchdog arm backstops any group whose
                # leader is mid-election right now.
                from apus_tpu.runtime.membership import \
                    request_join_all_groups
                try:
                    kwargs["group_cids"] = request_join_all_groups(
                        [p for i, p in enumerate(self.spec.peers)
                         if p and i != idx], self.spec.peers[idx], idx,
                        self.groups)
                except Exception:            # noqa: BLE001
                    pass                     # watchdog arm will retry
        d = self.daemon_cls(idx, self.spec, sm=self.sm_factory(),
                            recovery_start=True, seed=self.seed,
                            **kwargs)
        self.daemons[idx] = d
        d.start()
        return d

    def add_replica(self, timeout: float = 15.0) -> "ReplicaDaemon":
        """Grow the group: reserve an endpoint, run the join protocol
        against the current leader, then start the new replica — which
        catches up via normal adjustment/replication (plus a snapshot
        push if it is behind the leader's pruned head).  The AddServer /
        Upsize scenario of reconf_bench.sh:147-180."""
        sock = PeerServer.reserve()
        host, port = sock.getsockname()
        addr = f"{host}:{port}"
        try:
            slot, cid, peers = request_join(
                [p for p in self.spec.peers if p], addr, timeout=timeout)
        except BaseException:
            sock.close()               # release the reserved endpoint
            raise
        assert peers[slot] == addr, (slot, addr, peers)
        # Extend the shared spec in place so every current daemon (and
        # future restarts) sees the same slot-indexed peer table.
        while len(self.spec.peers) <= slot:
            self.spec.peers.append("")
        self.spec.peers[slot] = addr
        join_kwargs = dict(self.daemon_kwargs)
        if self.groups > 1:
            from apus_tpu.runtime.membership import \
                request_join_all_groups
            join_kwargs["group_cids"] = request_join_all_groups(
                [p for i, p in enumerate(self.spec.peers)
                 if p and i != slot], addr, slot, self.groups,
                timeout=timeout)
        d = self.daemon_cls(slot, self.spec, sm=self.sm_factory(), cid=cid,
                            listen_sock=sock, recovery_start=True,
                            seed=self.seed, **join_kwargs)
        while len(self.daemons) <= slot:
            self.daemons.append(None)
        self.daemons[slot] = d
        self.n = max(self.n, slot + 1)
        d.start()
        if self.groups > 1:
            missing = sorted(set(range(1, self.groups))
                             - set(join_kwargs.get("group_cids") or {}))
            if missing:
                d.retry_group_joins(addr, missing)
        return d

    def graceful_leave(self, idx: int, timeout: float = 15.0) -> None:
        """Operator-initiated graceful removal (OP_LEAVE) at the
        thread-cluster altitude: the leader commits the removal, the
        drained daemon flips to draining (stops voting/acking), and
        the harness — playing the CLI run loop's role — stops it."""
        from apus_tpu.runtime.membership import request_leave
        peers = [p for i, p in enumerate(self.spec.peers)
                 if p and i != idx and i < len(self.daemons)
                 and self.daemons[i] is not None]
        request_leave(peers, idx, timeout=timeout,
                      victim_addr=self.spec.peers[idx],
                      groups=self.groups)
        d = self.daemons[idx]
        if d is not None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline and not d.draining:
                time.sleep(0.01)
            assert d.draining, \
                f"replica {idx} never drained after its leave committed"
            d.stop()
            self.daemons[idx] = None

    def wait_caught_up(self, idx: int, timeout: float = 15.0) -> None:
        """Block until replica ``idx`` has applied everything committed
        cluster-wide at call time."""
        leader = self.wait_for_leader(timeout)
        with leader.lock:
            target = leader.node.log.commit
        deadline = time.monotonic() + timeout
        d = self.daemons[idx]
        if d is None:
            raise AssertionError(
                f"replica {idx} is not running (killed or never started); "
                f"cannot wait for catch-up")
        while time.monotonic() < deadline:
            with d.lock:
                if d.node.log.apply >= target:
                    return
            time.sleep(0.01)
        raise AssertionError(
            f"replica {idx} not caught up to {target} within {timeout}s")

    # -- invariants -------------------------------------------------------

    def check_logs_consistent(self) -> None:
        nodes = [d.node for d in self.live()]
        with_locks = [d.lock for d in self.live()]
        for lock in with_locks:
            lock.acquire()
        try:
            for node in nodes:
                node.log.check()
            min_commit = min(n.log.commit for n in nodes)
            for i in range(1, min_commit):
                dets = {n.log.get(i).determinant() for n in nodes
                        if n.log.head <= i < n.log.commit}
                assert len(dets) <= 1, f"divergent committed idx {i}: {dets}"
        finally:
            for lock in with_locks:
                lock.release()
