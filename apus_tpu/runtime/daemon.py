"""ReplicaDaemon: one live replica — protocol thread + peer server.

The reference runs consensus as a thread inside the application process
(proxy.c:76-81 -> dare_server_init -> ev_run, dare_server.c:173-238).
Our TPU-era split keeps the application untouched and runs consensus in a
separate daemon process per replica; the native proxy talks to it over a
unix socket + shared-memory commit counter (apus_tpu.runtime.bridge).

The daemon owns:
- the pure protocol ``Node`` (apus_tpu.core.node), ticked by a dedicated
  thread at sub-millisecond cadence (the libev loop analog,
  dare_server.c:216-238);
- a ``PeerServer`` exposing its regions/log to peers (the registered MRs);
- a ``NetTransport`` for its own one-sided ops to peers (the QPs);
- committed-entry upcalls: persistence + replay/release callbacks (the
  proxy callback table analog, dare_sm.h:42-47).

Thread-safety: a single RLock guards the node.  The tick thread holds it
for each tick but the transport releases it while blocked on the wire
(see apus_tpu.parallel.net docstring); peer-server handlers and client
submits take it for their short critical sections.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from apus_tpu.core.cid import Cid
from apus_tpu.core.log import LogEntry
from apus_tpu.core.node import Node, NodeConfig, PendingRequest
from apus_tpu.models.sm import StateMachine
from apus_tpu.models.kvs import KvsStateMachine
from apus_tpu.parallel.net import NetTransport, PeerServer
from apus_tpu.utils.config import ClusterSpec
from apus_tpu.utils.debug import make_logger


def _parse_peer(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def exclusion_silence(spec) -> float:
    """How long a non-leader tolerates total group silence before
    probing for eviction (shared by the in-place rejoin watchdog and,
    with margin, the daemon CLI's full re-exec backstop)."""
    return max(1.5, 20 * spec.hb_timeout)


class ReplicaDaemon:
    """One replica of the group, live on the network."""

    def __init__(self, idx: int, spec: ClusterSpec,
                 sm: Optional[StateMachine] = None,
                 cid: Optional[Cid] = None,
                 listen_sock=None,
                 tick_interval: float = 0.0005,
                 log_file: Optional[str] = None,
                 db_dir: Optional[str] = None,
                 recovery_start: bool = False,
                 seed: int = 0,
                 device_runner=None,
                 group_cids: Optional[dict] = None,
                 group_sm_factory=None,
                 live_groups: Optional[int] = None):
        self.idx = idx
        self.spec = spec
        self.lock = threading.RLock()
        self.logger = make_logger(f"apus.srv{idx}", log_file)
        self._tick_interval = tick_interval

        # Observability plane (apus_tpu.obs): one hub per replica —
        # shared metrics registry (all the stats views below), sampled
        # per-op stage spans, and the black-box flight recorder.
        # APUS_OBS=0 disables it; components then fall back to private
        # registries (the legacy stats surface stays alive).
        from apus_tpu.obs import make_hub
        self.obs = make_hub(ident=f"r{idx}")
        #: uptime anchor for the scrape's derived health verdict
        #: (leader flap RATE needs a denominator).
        self.started_mono = time.monotonic()

        # THE per-replica clock seam (utils/clock.py): every lease /
        # failure-detector time read in this daemon — tick stamps,
        # fresh-clock lease checks, heartbeat-delivery stamps, reply-
        # echo stamps — goes through this one callable, so the
        # adversarial-time nemesis can skew the WHOLE replica's notion
        # of time coherently (OP_FAULT clock_rate/clock_jump), exactly
        # like a machine with a drifting CLOCK_MONOTONIC.  Client-
        # facing deadlines and wire backoffs stay on real time (they
        # are mechanics, not protocol safety).
        from apus_tpu.utils.clock import SkewClock
        self.clock = SkewClock()

        peers = {i: _parse_peer(a) for i, a in enumerate(spec.peers)}
        # Dial backoff scaled to the timing envelope: at the production
        # envelope (hb=1 ms) a 0.5 s backoff would leave a transiently
        # unreachable peer unreplicated for hundreds of heartbeats.
        net = NetTransport(
            peers, yield_lock=self.lock,
            backoff=min(0.5, max(0.02, 2.0 * spec.hb_timeout)),
            stats=self.obs.view("net") if self.obs is not None else None)
        self.transport = net
        # Reply-echo stamps (lease renewal evidence) must share the
        # node's clock domain — they are compared against heartbeat
        # round-start stamps taken from the same seam.
        net.clock = self.clock
        # Live-stack fault plane (parallel.faults): only wraps when the
        # spec or APUS_FAULT_* env enables it — a production daemon's
        # transport is untouched.
        from apus_tpu.parallel.faults import maybe_wrap
        self.transport = maybe_wrap(self.transport, spec=spec,
                                    logger=self.logger, obs=self.obs)
        if self.transport is not net:
            # Adversarial-time scripting rides the fault plane's wire
            # op (OP_FAULT clock_rate / clock_jump / clock_reset).
            self.transport.clock_ctl = self.clock
        cfg = NodeConfig(
            idx=idx, n_slots=spec.n_slots, hb_period=spec.hb_period,
            hb_timeout=spec.hb_timeout, elect_low=spec.elect_low,
            elect_high=spec.elect_high, prune_period=spec.prune_period,
            max_batch=spec.max_batch, auto_remove=spec.auto_remove,
            fail_window=spec.fail_window, recovery_start=recovery_start,
            seed=seed,
            read_lease=spec.read_lease, lease_margin=spec.lease_margin,
            follower_read_leases=getattr(spec, "follower_read_leases",
                                         True),
            # Bucket-granular follower leases (per-key Hermes write
            # invalidation); env overrides the spec either way so the
            # A/B bench can pin the whole-log baseline per process.
            flr_bucket_leases=(
                os.environ["APUS_FLR_BUCKETS"] not in ("0", "false")
                if "APUS_FLR_BUCKETS" in os.environ
                else getattr(spec, "flr_bucket_leases", True)),
            # Planted-stale-lease harness knob (tests only): makes one
            # follower's lease deliberately wrong so the audit plane
            # must catch the resulting stale read.
            flr_plant=os.environ.get("APUS_FLR_PLANT", ""),
            # Segment oversized records so every entry stays device-
            # eligible (slot width minus wire-codec + envelope headroom;
            # DeviceCommitRunner.max_data_bytes is the contract).  With
            # the multi-controller mesh plane enabled, its slot width
            # governs too — entries must fit the NARROWEST device slot.
            seg_chunk=max(0, min(spec.slot_bytes,
                                 spec.mesh_slot_bytes
                                 if spec.mesh_n > 0 else spec.slot_bytes)
                          - 128))
        #: kept for the multi-group runtime: extra groups clone this
        #: config with a per-gid rng phase (runtime/groupset.py).
        self._node_cfg = cfg
        self.node = Node(cfg, cid or Cid.initial(spec.group_size),
                         sm or KvsStateMachine(), self.transport)
        if self.obs is not None:
            # node_* counters land in the shared registry; span stamps
            # and flight notes engage (sim nodes never attach).
            self.node.attach_obs(self.obs)
        # Incarnation fencing: a joiner's tenancy starts at the epoch
        # of the CONFIG that admitted it (the cid the join reply
        # carried); static members start at 0.  The transport stamps
        # the live value onto every outbound ctrl write.
        if cid is not None:
            self.node.incarnation = cid.epoch
        net.incarnation_of = lambda: self.node.incarnation
        # Graceful-leave drain (OP_LEAVE): set once OUR removal is
        # committed — watchdogs stop re-joining, the node stops
        # voting/acking, and the CLI run loop exits clean.
        self.draining = False
        # Lease-validity checks must see FRESH time, not the tick-start
        # stamp: an isolated leader's tick stalls in heartbeat write
        # timeouts with the lock yielded, freezing the stamp exactly
        # while client handler threads keep consulting the lease.  The
        # fresh clock is the daemon's SkewClock, so injected skew
        # reaches the lease math through the same seam.
        self.node.clock = self.clock
        # Follower linearizable reads (runtime.flr): install the lease
        # requester; Node gates everything on cfg.follower_read_leases.
        from apus_tpu.runtime.flr import install_flr
        install_flr(self)
        # Per-replica read service-capacity emulation for the follower-
        # read throughput bench on single-core boxes (bench.py
        # --throughput): each served read holds this daemon's service
        # gate for APUS_READ_SVC_US microseconds, emulating a replica
        # that owns one core.  0 (default) = off, zero overhead.
        try:
            self.read_svc = float(os.environ.get("APUS_READ_SVC_US",
                                                 "0") or 0) / 1e6
        except ValueError:
            self.read_svc = 0.0
        self._svc_gate = threading.Lock()
        # Live deployments stream snapshots off-tick (a multi-second
        # chunked push inline would pause this replica's heartbeats);
        # the deterministic sim keeps the inline path.
        self.node.async_snap_push = True
        # Fresh-start grace: randomize the first election timeout so a
        # cold cluster elects cleanly (dare_server.c:1237).  Stamped
        # from the daemon clock — _last_hb_seen lives in that domain.
        self.node._last_hb_seen = (self.clock()
                                   + self.node.rng.random()
                                   * self.node.cfg.elect_high)

        host, port = peers.get(idx, ("127.0.0.1", 0))
        self.server = PeerServer(lambda: self.node, self.lock,
                                 host=host, port=port, sock=listen_sock,
                                 extra_ops=self._extra_ops(),
                                 logger=self.logger,
                                 stats=self.obs.view("srv")
                                 if self.obs is not None else None)
        # Multi-group sharded consensus (Multi-Raft; runtime/groupset):
        # spec.groups independent consensus groups multiplexed over
        # THIS daemon's sockets/transport/fault plane/clock.  Group 0
        # is self.node (membership discovery, persistence, bridge);
        # extra groups ride OP_GROUP-wrapped frames and the coalesced
        # per-peer OP_HB_MULTI heartbeat.  groups == 1 (default):
        # nothing is built, no hb_sink is installed, and every wire
        # frame stays byte-identical to the single-group protocol.
        self.n_groups = max(1, int(getattr(spec, "groups", 1) or 1))
        if group_cids:
            # Elastic groups: a joiner admitted into split-born groups
            # beyond the static config builds nodes for them too.
            self.n_groups = max(self.n_groups, max(group_cids) + 1)
        if live_groups:
            # ...including groups whose admission timed out at boot
            # (the background retry finishes those; their nodes must
            # exist to receive catch-up replication meanwhile).
            self.n_groups = max(self.n_groups, live_groups)
        self.groupset = None
        #: Elastic-group plane (runtime/elastic.py): shard-map view,
        #: bucket-ownership admission fence, and the migration driver.
        #: None on single-group daemons — zero cost there.
        self.elastic = None
        if self.n_groups > 1:
            from apus_tpu.runtime.groupset import GroupSet
            gs_kwargs = {}
            if group_sm_factory is not None:
                gs_kwargs["sm_factory"] = group_sm_factory
            self.groupset = GroupSet(self, self.n_groups,
                                     cids=group_cids, **gs_kwargs)
            self.server.group_ref = self.groupset.port

        # Per-group write service-capacity emulation for the multi-
        # group throughput bench (bench.py --throughput --groups):
        # each admitted write holds ITS GROUP's service gate for
        # APUS_WRITE_SVC_US microseconds at the leader, emulating a
        # deployment where every group's leader owns a core — the
        # exact sibling of APUS_READ_SVC_US above.  0 (default) = off,
        # zero overhead.
        try:
            self.write_svc = float(os.environ.get("APUS_WRITE_SVC_US",
                                                  "0") or 0) / 1e6
        except ValueError:
            self.write_svc = 0.0
        self._wsvc_gates: dict[int, threading.Lock] = {}

        # Pipelined client bursts: admit a whole burst of client ops
        # under one lock acquisition + one commit wait (group-commit
        # admission; see make_client_batch_hook).
        from apus_tpu.runtime.client import make_client_batch_hook
        self.server.batch_hook = make_client_batch_hook(self)

        # Overload control plane (ISSUE 17; runtime/overload.py):
        # bounded in-flight budgets + typed ST_OVERLOAD shedding for
        # client data ops, enforced at the PeerServer ingest, the
        # group-commit drain (deadline sheds), and — when enabled —
        # natively in the C++ plane.  Budgets default generous (normal
        # workloads never trip them); APUS_OVL_* shrinks them for
        # saturation campaigns.  Control traffic NEVER passes through
        # the gate: overload cannot burn a leadership.
        from apus_tpu.runtime.overload import OverloadPolicy
        self.overload = OverloadPolicy.from_env(
            self.client_op_timeout,
            stats=self.obs.view("srv") if self.obs is not None else None,
            flight=self.obs.flight if self.obs is not None else None)
        self.server.overload = self.overload

        # Committed-entry observers (proxy callback table analog):
        # each gets (LogEntry); registered by persistence/replay layers.
        self.on_commit: list[Callable[[LogEntry], None]] = []
        # Per-tick observers, called under the node lock after upcalls —
        # used by the bridge to mirror role/term into shared memory
        # synchronously with role transitions (no stale-flag window).
        self.on_tick: list[Callable[[], None]] = []
        # Snapshot-install observers: (Snapshot, ep_dump) after a
        # leader-pushed snapshot replaced local state (persistence must
        # record it; a proxied replica's bridge re-primes its app).
        self.on_snapshot: list[Callable] = []

        # Durable store (stable storage, db-interface.c analog).  On
        # restart with an existing store, replay it into the SM and
        # endpoint DB first: catch-up re-replication then hits the
        # apply-time dedup, so commands are neither re-executed nor
        # re-persisted (the reference replays its BDB dump the same way,
        # proxy.c:306-339).
        self.persistence = None
        #: disk-fault observability (OP_STATUS): I/O errors seen on the
        #: persistence path, and whether they disabled it for the
        #: session (the replica keeps serving; acked-write durability
        #: is replication's job — see Persistence docstring)
        self.persist_errors = 0
        self.persist_disabled = False
        if db_dir is not None:
            from apus_tpu.runtime.persist import (Persistence,
                                                  daemon_store_path)
            # Inbound snapshot streams assemble (and survive restarts)
            # next to the durable store: a transfer interrupted by OUR
            # crash resumes from the last acked chunk after restart.
            self.node.snap_spool_dir = db_dir
            self.persistence = Persistence(
                daemon_store_path(db_dir, idx),
                sync_policy=getattr(spec, "sync_policy", "batch"),
                logger=self.logger)
            if self.persistence.store.count:
                self.persistence.replay_into(self.node.sm, self.node.epdb,
                                             node=self.node)
            self.on_commit.append(self._persist_commit)
            self.on_snapshot.append(self._persist_snapshot)
            if self.groupset is not None:
                # Per-group durability (elastic-group plane): every
                # extra group gets its own store under the same db dir
                # and replays/re-bases independently; store files
                # beyond the static count re-create their (split-born)
                # groups first.
                self.groupset.attach_persistence(db_dir)

        # Elastic groups (runtime/elastic.py): online SPLIT/MERGE of
        # the bucketed keyspace across consensus groups.  Built only
        # with the multi-group runtime; constructed AFTER persistence
        # replay so the first shard-map recompute sees recovered
        # migration state.
        if self.groupset is not None:
            from apus_tpu.runtime.elastic import (ElasticPlane,
                                                  make_elastic_ops)
            self.elastic = ElasticPlane(self)
            self.server._extra_ops.update(make_elastic_ops(self))

        # Transaction plane (runtime/txn.py): the OP_TXN service runs
        # on EVERY daemon (single-group MULTI batches are one TM log
        # entry, no 2PC); the cross-group coordinator/recovery driver
        # starts only with the multi-group runtime.
        from apus_tpu.runtime.txn import TxnPlane, make_txn_ops
        self.txn = TxnPlane(self)
        self.server._extra_ops.update(make_txn_ops(self))

        # Native serving data plane (parallel/native_plane.py +
        # native/dataplane.cpp): the GIL-released C++ hot path for
        # client ingest -> dedup -> group-commit -> reply.  Built only
        # when ClusterSpec.native_plane / APUS_NATIVE_PLANE asks for it
        # and the extension is present (absent = LOUD fallback to the
        # pure-Python plane — identical wire behavior either way).
        from apus_tpu.parallel.native_plane import maybe_build
        self.native = maybe_build(self)
        if self.native is not None:
            # Applied-view maintenance + per-tick gate publishing run
            # under the node lock at apply/tick time; snapshot installs
            # rebuild the view (or poison it at large state).
            self.on_commit.append(self.native.on_entry_applied)
            self.on_snapshot.append(self.native.on_snapshot_installed)
            self.on_tick.append(self.native.publish_gates)

        # Device plane (runtime.device_plane): the jitted commit step as
        # the primary replication/quorum engine, host TCP as control
        # plane + catch-up (the RC-data/UD-control split of the
        # reference, SURVEY §5.8).  A multi-controller runner
        # (runtime.mesh_plane) additionally binds to this daemon for
        # term checks and registers its descriptor op on the peer
        # server.
        self.device_driver = None
        if device_runner is not None \
                and getattr(device_runner, "group_major", False):
            # Group-major engine (runtime.group_plane): one driver
            # thread serves ALL of this daemon's consensus groups —
            # many groups' windows per device dispatch.
            from apus_tpu.runtime.group_plane import GroupPlaneDriver
            self.device_driver = GroupPlaneDriver(self, device_runner)
        elif device_runner is not None:
            from apus_tpu.runtime.device_plane import DevicePlaneDriver
            if hasattr(device_runner, "attach"):
                device_runner.attach(self)
            if hasattr(device_runner, "on_descriptor"):
                from apus_tpu.parallel.faults import FaultPlane
                from apus_tpu.runtime.mesh_plane import OP_MESH
                handler = device_runner.on_descriptor
                if isinstance(self.transport, FaultPlane):
                    # Mesh descriptor channel rides the fault plane
                    # too: a dropped descriptor NACKs the leader's
                    # feed, deterministically exercising plane
                    # degradation + re-formation.
                    handler = self.transport.wrap_handler("mesh", handler)
                self.server._extra_ops[OP_MESH] = handler
            self.device_driver = DevicePlaneDriver(self, device_runner)

        self._stop = threading.Event()
        self._tick_thread: Optional[threading.Thread] = None
        self._excl_thread: Optional[threading.Thread] = None
        self._compact_thread: Optional[threading.Thread] = None
        self._last_role = None
        # Client-facing handlers wait on this instead of polling the
        # lock (K pollers at 0.2 ms would starve the tick thread).
        # Wakes are WINDOW-GRANULAR: the tick thread notifies only when
        # a waiter-visible event happened this tick (apply/commit
        # advanced, role/term changed, a read was served) — not every
        # tick, which at 0.5 ms cadence thrashed every parked handler
        # thread 2000x/s for nothing.
        self.commit_cond = threading.Condition(self.lock)
        self._wake_state = None

    # -- extra (two-sided) control ops ------------------------------------

    #: how long a client-facing handler blocks waiting for commit/apply
    client_op_timeout: float = 5.0

    def _extra_ops(self) -> dict:
        from apus_tpu.parallel.faults import FaultPlane, make_fault_ops
        from apus_tpu.runtime.client import make_client_ops
        from apus_tpu.runtime.flr import make_flr_ops
        from apus_tpu.runtime.membership import make_membership_ops
        ops = {**make_client_ops(self), **make_membership_ops(self),
               **make_flr_ops(self)}
        if self.obs is not None:
            # OP_METRICS scrape + OP_OBS_DUMP flight/span readout.
            from apus_tpu.obs.service import make_obs_ops
            ops.update(make_obs_ops(self))
        if isinstance(self.transport, FaultPlane):
            # Remote fault scripting: tests compose cluster-wide
            # partitions by scripting each member's plane over the wire.
            ops.update(make_fault_ops(self))
        return ops

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self.native is not None:
            # Armed before the listener: a client connection accepted
            # on the very first frame must find the plane running.
            self.native.start()
            self.server.native_plane = self.native
        self.server.start()
        t = threading.Thread(target=self._run, name=f"apus-tick-{self.idx}",
                             daemon=True)
        t.start()
        self._tick_thread = t
        w = threading.Thread(target=self._exclusion_watchdog,
                             name=f"apus-excl-{self.idx}", daemon=True)
        w.start()
        self._excl_thread = w
        if self.persistence is not None \
                and getattr(self.spec, "compact_retain", 0) > 0:
            cw = threading.Thread(target=self._compaction_watchdog,
                                  name=f"apus-compact-{self.idx}",
                                  daemon=True)
            cw.start()
            self._compact_thread = cw
        if self.device_driver is not None:
            self.device_driver.start()
        if self.elastic is not None:
            # Migration driver: resumes any open migration this daemon
            # comes to lead (leader kill mid-migration moves the driver
            # with the leadership).
            self.elastic.start()
        if self.txn is not None and self.groupset is not None:
            # 2PC recovery driver: resumes any open coordinator txn
            # this daemon comes to lead (same idiom as the elastic
            # driver — a coordinator kill mid-2PC moves the driver).
            self.txn.start()
        # Arm any loaded fault schedule now that the daemon serves —
        # schedule time 0 is "daemon up", not "object constructed".
        if hasattr(self.transport, "arm"):
            self.transport.arm()
        self.logger.info("daemon %d up at %s", self.idx, self.server.addr)

    def stop(self) -> None:
        self._stop.set()
        if self.txn is not None:
            self.txn.stop()
        if self.elastic is not None:
            self.elastic.stop()
        if self.device_driver is not None:
            self.device_driver.stop()
            if hasattr(self.device_driver.runner, "stop"):
                self.device_driver.runner.stop()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=2.0)
        if self._excl_thread is not None:
            self._excl_thread.join(timeout=2.0)
        self.server.stop()
        if self.native is not None:
            # RST-closes every adopted client connection (crash-fault
            # fidelity, matching PeerServer.stop) and joins the loop.
            self.native.stop()
        if hasattr(self.transport, "stop"):
            self.transport.stop()       # fault-plane schedule thread
        self.transport.close()
        if self._compact_thread is not None:
            self._compact_thread.join(timeout=2.0)
        if self.persistence is not None:
            self.persistence.close()
        # Close (do NOT delete) any half-assembled inbound snapshot
        # stream: the partial file + checkpoint sidecar in the spool
        # dir are the RESUME anchor — our next incarnation hands the
        # sender its verified progress instead of re-fetching from
        # byte zero.  (Spool-less nodes leave only a tempfile behind,
        # reaped with the tempdir.)
        from apus_tpu.parallel.onesided import _snap_session_close
        _snap_session_close(self.node)
        if self.groupset is not None:
            for gnode in self.groupset.nodes[1:]:
                _snap_session_close(gnode)
            for p in self.groupset.persists.values():
                try:
                    p.close()
                except OSError:
                    pass

    def begin_drain(self, why: str) -> None:
        """Graceful leave: our removal is COMMITTED cluster-wide
        (either we applied the replicated ``leave <slot>`` marker, or
        the operator's mode-1 notify confirmed it).  From here on this
        replica never votes, never acks, never re-joins; the CLI run
        loop exits 0 and in-process harnesses stop the daemon.
        Idempotent."""
        with self.lock:
            if self.draining:
                return
            self.draining = True
            self.node.draining = True
            if self.groupset is not None:
                self.groupset.begin_drain()
        self.logger.info("graceful leave: draining (%s); this replica "
                         "stops voting/serving and will exit clean", why)

    def _exclusion_watchdog(self) -> None:
        """Self-rejoin after eviction, for EVERY deployment shape.

        A replica the failure detector removed receives nothing ever
        again (it is nobody's replication target and PreVote keeps it
        from bumping terms) — and eviction can land at ANY time,
        including moments after a restart passed its not-excluded
        check.  This thread watches for sustained silence while not
        leading, and when some live leader's membership excludes our
        slot, re-enters the group IN PLACE through the join protocol:
        the leader re-admits the slot (handle_join reuses it — lowest
        empty bit), replication to us resumes, and applying the CONFIG
        entries teaches us the new cid.  No restart needed.  The
        daemon-CLI re-exec path (run loop) remains as the full-reset
        backstop for process deployments."""
        from apus_tpu.runtime.membership import request_join

        silence = max(1.5, 20 * self.spec.hb_timeout)
        last_try = 0.0
        while not self._stop.is_set():
            self._stop.wait(0.25)
            # hb_age compares against _last_hb_seen, which lives in the
            # daemon-clock domain (tick stamps + HB delivery stamps).
            now = self.clock()
            with self.lock:
                is_leader = self.node.is_leader
                hb_age = now - self.node._last_hb_seen
            # hb_age < 0 covers the future-stamped cold-start grace.
            if is_leader or hb_age < silence or now - last_try < 2.0:
                continue
            if self.draining:
                # Graceful leave: exclusion is INTENTIONAL — never
                # rejoin (the whole point of OP_LEAVE vs auto-remove).
                continue
            last_try = now
            if not _excluded_by_live_leader(self, self.spec):
                continue
            my_addr = self.spec.peers[self.idx] \
                if self.idx < len(self.spec.peers) else ""
            if not my_addr:
                continue
            self.logger.error(
                "removed from the group (a live leader excludes slot "
                "%d); re-joining in place at %s", self.idx, my_addr)
            if self.obs is not None:
                self.obs.flight.note("watchdog", "exclusion_rejoin",
                                     slot=self.idx)
            try:
                slot, cid, jpeers = request_join(
                    [p for i, p in enumerate(self.spec.peers)
                     if p and i != self.idx], my_addr, timeout=5.0,
                    want_slot=self.idx)
                # Adopt the reply's peer table: members that joined
                # after our boot config (their addresses are needed to
                # probe/rejoin the EXTRA groups, whose leaders may
                # live there).
                for i, p in enumerate(jpeers):
                    if not p or i == self.idx:
                        continue
                    while len(self.spec.peers) <= i:
                        self.spec.peers.append("")
                    if self.spec.peers[i] != p:
                        self.spec.peers[i] = p
                        host, port_s = p.rsplit(":", 1)
                        self.transport.set_peer(i, (host, int(port_s)))
                if slot != self.idx:
                    self.logger.error(
                        "rejoin assigned slot %d != ours (%d); leaving "
                        "re-admission to the operator", slot, self.idx)
                    return
                with self.lock:
                    # Fresh tenancy: adopt the admission epoch NOW so
                    # our ctrl writes clear the peers' removed-slot
                    # fence immediately (applying our own re-add entry
                    # during catch-up would bump it too, but our acks
                    # would be fenced until then).
                    self.node.incarnation = max(self.node.incarnation,
                                                cid.epoch)
                self.logger.info("re-admitted at slot %d (incarnation "
                                 "%d)", slot, cid.epoch)
            except Exception as e:               # noqa: BLE001
                self.logger.warning("rejoin attempt failed: %s", e)
            # Multi-group: the eviction removed this slot from EVERY
            # group whose failure detector saw the silence — rejoin
            # the extra groups too (idempotent where still a member).
            self._rejoin_extra_groups(my_addr)

    def retry_group_joins(self, my_addr: str, gids) -> None:
        """Finish deferred extra-group admissions in the background
        (request_join_all_groups skips groups whose join timed out at
        boot — a group mid-election/mid-resize under churn): keep
        retrying each until admitted or permanently refused.

        A typed refusal is treated as permanent only after it REPEATS:
        right after a slot re-admission, an extra group's leader can
        still hold the slot's OLD address binding (its peer table
        updates when the group-0 re-add CONFIG applies there), so the
        first few ``slot_bound`` answers are expected convergence
        noise, not a verdict — giving up on the first one left the
        joiner silently outside the group forever (the elastic
        campaign's seed 27103 wedge)."""
        from apus_tpu.runtime.membership import (JoinRefusedError,
                                                 request_join_group)
        gids = sorted(gids)
        if not gids:
            return

        def run():
            left = list(gids)
            refusals: dict[int, int] = {}
            while left and not self._stop.is_set():
                for gid in list(left):
                    peers = [p for i, p in enumerate(self.spec.peers)
                             if p and i != self.idx]
                    try:
                        cid = request_join_group(peers, my_addr, gid,
                                                 self.idx, timeout=10.0)
                    except JoinRefusedError as e:
                        refusals[gid] = refusals.get(gid, 0) + 1
                        if refusals[gid] >= 8:
                            self.logger.error(
                                "group %d join permanently refused "
                                "(%d consecutive): %s", gid,
                                refusals[gid], e)
                            left.remove(gid)
                        continue
                    except Exception:        # noqa: BLE001
                        continue             # retry next round
                    refusals.pop(gid, None)
                    gnode = self.group_node(gid)
                    if gnode is not None:
                        with self.lock:
                            gnode.incarnation = max(gnode.incarnation,
                                                    cid.epoch)
                    self.logger.info(
                        "group %d admitted at slot %d (deferred join, "
                        "incarnation %d)", gid, self.idx, cid.epoch)
                    left.remove(gid)
                self._stop.wait(1.0)

        threading.Thread(target=run, daemon=True,
                         name=f"apus-gjoin-{self.idx}").start()

    def _rejoin_extra_groups(self, my_addr: str) -> None:
        """In-place rejoin of extra consensus groups whose live leader
        excludes our slot (the per-group arm of the exclusion
        watchdog).  Best effort per group; a group that still lists us
        answers the join idempotently."""
        if self.groupset is None:
            return
        from apus_tpu.runtime.client import probe_status
        from apus_tpu.runtime.membership import request_join_group
        peers = [p for i, p in enumerate(self.spec.peers)
                 if p and i != self.idx]
        for gid in range(1, self.n_groups):
            gnode = self.groupset.node(gid)
            if gnode is None:
                continue
            excluded = False
            for addr in peers:
                st = probe_status(addr, timeout=0.3)
                gst = ((st or {}).get("groups") or {}).get(str(gid))
                if (gst is not None and gst.get("is_leader")
                        and gst.get("term", 0) >= gnode.current_term
                        and self.idx not in gst.get("members", [])):
                    excluded = True
                    break
            if not excluded:
                continue
            try:
                cid = request_join_group(peers, my_addr, gid, self.idx,
                                         timeout=5.0)
                with self.lock:
                    gnode.incarnation = max(gnode.incarnation,
                                            cid.epoch)
                self.logger.info("group %d re-admitted at slot %d "
                                 "(incarnation %d)", gid, self.idx,
                                 cid.epoch)
            except Exception as e:               # noqa: BLE001
                self.logger.warning("group %d rejoin failed: %s",
                                    gid, e)

    def _compaction_watchdog(self) -> None:
        """Bounded restart replay: once the durable store accumulates
        more than ``spec.compact_retain`` records past its last base
        image, fold the applied prefix into a fresh base (Persistence
        compaction — see persist.py's phase walkthrough).  The capture
        and the final swap take the node lock briefly; the O(state)
        I/O runs here, off the tick thread, while appends queue."""
        period = max(0.5, getattr(self.spec, "compact_check_period",
                                  5.0))
        retain = getattr(self.spec, "compact_retain", 0)
        while not self._stop.is_set():
            self._stop.wait(period)
            if self._stop.is_set():
                return
            # Per-group compaction floors (elastic-group durability):
            # group 0 plus every extra group's store, each folded
            # independently against the same retention window.
            stores = []
            if not self.persist_disabled and self.persistence is not None:
                stores.append((self.node, self.persistence))
            if self.groupset is not None:
                for gid, p in self.groupset.persists.items():
                    if not self.groupset.persist_disabled.get(gid):
                        stores.append((self.groupset.nodes[gid], p))
            for node, p in stores:
                if self._stop.is_set():
                    return
                if p.entries_since_base <= retain:
                    continue
                cap = None
                try:
                    with self.lock:
                        cap = p.begin_compact(node)
                    if cap is None:
                        continue
                    p.prepare_compact(cap)
                    with self.lock:
                        p.finish_compact(cap)
                    if self.obs is not None:
                        self.obs.flight.note(
                            "watchdog", "compaction", gid=node.gid,
                            floor=p.compaction_floor)
                except OSError as exc:
                    # A failed compaction leaves the OLD store
                    # authoritative (abort drains the queued appends
                    # back into it) — log and retry later; never
                    # disable persistence for it.
                    self.logger.warning("store compaction failed "
                                        "(g%d): %s", node.gid, exc)
                    with self.lock:
                        p.abort_compact(cap)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                with self.lock:
                    now = self.clock()
                    self.node.tick(now)
                    self._drain_upcalls()
                    if self.groupset is not None:
                        # Extra groups tick under the SAME lock hold,
                        # then every group's registered heartbeat round
                        # flushes as one coalesced OP_HB_MULTI frame
                        # per peer (the lock is yielded on the wire).
                        self.groupset.tick(now)
                        self.groupset.flush_heartbeats()
                    self._log_role_changes()
                    for cb in self.on_tick:
                        cb()
                    n = self.node
                    # Waiter-predicate contract: every commit_cond
                    # waiter's wake condition must be a function of
                    # this tuple — reply/done/join sentinels are set
                    # during apply (apply moves), served reads bump
                    # reads_done, leadership loss moves role/term, and
                    # log.end covers append-only progress (a pipelined
                    # burst's deferred read registration waits on its
                    # writes entering the log).  Deadline expiry needs
                    # no notify: every waiter bounds its wait by the
                    # time left to its own deadline.  Extra groups
                    # contribute their own tuples (their waiters park
                    # on the same condition).
                    wake = (n.log.apply, n.log.commit, n.log.end,
                            n.role, n.current_term, n.reads_done)
                    if self.groupset is not None:
                        wake = (wake, self.groupset.wake_state())
                    if wake != self._wake_state:
                        self._wake_state = wake
                        self.commit_cond.notify_all()
            except Exception:
                # A tick must never silently kill the replica (a dead
                # tick thread with a live PeerServer is a zombie that
                # still acks writes).  Log and keep ticking; persistent
                # faults will surface via the failure detector.
                self.logger.exception("tick failed")
            time.sleep(self._tick_interval)

    # -- persistence wrappers (disk-fault containment) ---------------------
    #
    # Every durable-store touch runs on the tick thread (via
    # _drain_upcalls) — an unhandled ENOSPC/EIO there either killed the
    # snapshot record forever (the upcall list was already drained) or
    # log-spammed every tick.  Policy: FIRST I/O error disables
    # persistence for the session, loudly, and the replica keeps
    # serving — acked-write durability is replication's job; the local
    # store only narrows full-cluster-power-loss exposure (DESIGN.md
    # "durability & recovery semantics").  Disabling (rather than
    # limping on) also keeps the on-disk store a valid PREFIX of the
    # applied log: skipping one failed record and appending later ones
    # would corrupt the restart replay.

    def _persist_fail(self, stage: str, exc: OSError) -> None:
        self.persist_errors += 1
        if self.persist_disabled:
            return
        self.persist_disabled = True
        if self.obs is not None:
            self.obs.flight.note("persist", "disabled", stage=stage,
                                 error=repr(exc))
        self.logger.error(
            "PERSISTENCE DISABLED for this session: %s failed (%s); "
            "continuing to serve — durability of acked writes remains "
            "replication; restart recovery will replay the store's "
            "valid prefix + catch up from peers", stage, exc)

    def _persist_commit(self, e: LogEntry) -> None:
        if self.persist_disabled:
            return
        try:
            self.persistence.on_commit(e)
        except OSError as exc:
            self._persist_fail("entry append", exc)

    def _persist_snapshot(self, snap, ep_dump) -> None:
        if self.persist_disabled:
            return
        try:
            self.persistence.on_snapshot(snap, ep_dump)
        except OSError as exc:
            self._persist_fail("snapshot record", exc)

    def _persist_flush(self) -> None:
        if self.persist_disabled:
            return
        try:
            self.persistence.flush_window()
        except OSError as exc:
            self._persist_fail("fsync", exc)

    def _drain_upcalls(self) -> None:
        if self.node.snapshot_upcalls:
            snaps, self.node.snapshot_upcalls = \
                self.node.snapshot_upcalls, []
            if self.elastic is not None:
                # An install may have replaced group 0's migration
                # tables wholesale (they ride the reserved key).
                self.elastic.dirty = True
            for snap, ep_dump in snaps:
                # A FILE-backed capture is only streamable while the
                # SM's dump generation still matches (another install
                # replaced the file otherwise) — stale captures are
                # dropped; the superseding install's own upcall follows
                # later in this same ordered list.
                if snap.data_path is not None and snap.data_gen != \
                        getattr(self.node.sm, "dump_generation", 0):
                    continue
                for cb in self.on_snapshot:
                    cb(snap, ep_dump)
        if self.node.config_upcalls:
            cfgs, self.node.config_upcalls = self.node.config_upcalls, []
            for e in cfgs:
                self._handle_config_entry(e)
        if self.node.committed_upcalls:
            entries, self.node.committed_upcalls = \
                self.node.committed_upcalls, []
            if self.elastic is not None:
                for e in entries:
                    if e.data[:1] != b"M":
                        continue
                    # Migration record applied in group 0: the derived
                    # shard map must recompute before the next
                    # admission; a split's freeze record additionally
                    # creates the dst group from its replicated
                    # genesis cid.
                    self.elastic.dirty = True
                    if e.data[:2] == b"MB":
                        self.elastic.ensure_from_begin(e.data)
            for e in entries:
                for cb in self.on_commit:
                    cb(e)
            applied_this_tick = True
        else:
            applied_this_tick = False
        if self.persistence is not None:
            # Batch sync policy: ONE fdatasync per drain window,
            # amortized over every record this tick appended (entries
            # and snapshot records alike); no-op when nothing appended.
            self._persist_flush()
            if applied_this_tick and self.obs is not None \
                    and not self.persist_disabled:
                # Stage span: the drain window's batch fdatasync now
                # covers every sampled op applied this tick.
                self.obs.spans.stamp_have("fsync", require="apply")

    def _handle_config_entry(self, e: LogEntry) -> None:
        """Applied CONFIG entry: learn new peers (the poll_config_entries
        follower side, dare_server.c:2133-2187).  Join entries carry
        ``"<slot> <addr>"`` in data."""
        if e.data:
            if e.data.startswith(b"leave "):
                # Graceful-leave marker (Node.handle_leave): the
                # removal reason is replicated, so the drained member
                # — whichever replica it is — learns its removal was
                # intentional the moment it applies the entry.
                try:
                    left = int(e.data.split(b" ", 1)[1])
                except ValueError:
                    self.logger.warning("bad LEAVE payload %r", e.data)
                    return
                if left == self.idx:
                    self.begin_drain("applied own leave entry")
                return
            try:
                slot_s, addr = e.data.decode().split(" ", 1)
                slot = int(slot_s)
            except ValueError:
                self.logger.warning("bad CONFIG payload %r", e.data)
                return
            if slot != self.idx:
                self.transport.set_peer(slot, _parse_peer(addr))
            # Shared-spec peer table: idempotent slot-indexed write (all
            # daemons of a LocalCluster share one spec object).
            peers = self.spec.peers
            while len(peers) <= slot:
                peers.append("")
            peers[slot] = addr
            self.logger.info("CONFIG: slot %d -> %s (%r)", slot, addr,
                             e.cid)

    def _log_role_changes(self) -> None:
        role = (self.node.role, self.node.current_term)
        if role != self._last_role:
            self._last_role = role
            if self.obs is not None:
                # Black box: role/term transitions, edge-triggered.
                self.obs.flight.note(
                    "role", self.node.role.name,
                    term=self.node.current_term,
                    commit=self.node.log.commit)
            # Leader banner greppable by ops tooling, matching the
            # "[T<term>] LEADER" lines run.sh greps (run.sh:46-68).
            if self.node.is_leader:
                self.logger.info("[T%d] LEADER", self.node.current_term)
            else:
                self.logger.info("[T%d] %s", self.node.current_term,
                                 self.node.role.name)

    # -- client-facing API ------------------------------------------------

    def group_node(self, gid: int):
        """The Node of consensus group ``gid`` (0 = the primary), or
        None for unknown gids."""
        if gid == 0:
            return self.node
        if self.groupset is None:
            return None
        return self.groupset.node(gid)

    @property
    def is_leader(self) -> bool:
        return self.node.is_leader

    @property
    def term(self) -> int:
        return self.node.current_term

    @property
    def leader_hint(self) -> Optional[int]:
        return self.node.leader_hint

    def submit(self, req_id: int, clt_id: int,
               data: bytes) -> Optional[PendingRequest]:
        with self.lock:
            return self.node.submit(req_id, clt_id, data)

    def wait_committed(self, pr: PendingRequest,
                       timeout: float = 5.0) -> bool:
        """Block until the request is applied (the proxy release analog,
        proxy_update_state proxy.c:263-267).  Success is gated on the
        reply sentinel — commit/apply position alone can be satisfied by
        a DIFFERENT entry after a truncation.  Wakes are event-driven
        (the tick thread notifies per applied window / role change);
        the residual 0.25 s wait cap is only a missed-wake backstop,
        never on the latency path: completion events notify (see the
        wake-tuple contract in _run), and deadline expiry is exact
        because the final wait is bounded by ``left`` itself — the old
        fixed 0.05 s cap, by contrast, was the completion mechanism
        and added up to 50 ms of tail latency per op."""
        deadline = time.monotonic() + timeout
        with self.commit_cond:
            while True:
                if pr.reply is not None:
                    return True
                if not self.node.is_leader:
                    return False      # lost leadership: client must retry
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self.commit_cond.wait(min(left, 0.25))


# -- CLI: one replica as a standalone OS process ---------------------------
#
# The reference deploys one server process per machine (benchmarks/
# run.sh:23-31 over ssh), configured by env vars (server_idx/group_size/
# server_type/config_path/dare_log_file, proxy.c:22-89) plus a shared
# config file.  This CLI is that contract: `python -m
# apus_tpu.runtime.daemon --idx I --config cluster.json ...` runs ONE
# replica — daemon + (optionally) bridge + app-under-interposer — until
# SIGTERM.  Multi-host deployment = run it on each host with the same
# config; the local multi-process launcher is apus_tpu.runtime.proc.

def main(argv: Optional[list] = None) -> int:
    import argparse
    import json as _json
    import os
    import shlex
    import signal
    import subprocess
    import sys

    from apus_tpu.utils.config import ProcessEnv, load_config

    env = ProcessEnv.from_env()
    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.runtime.daemon",
        description="One APUS-TPU replica as a standalone process.")
    ap.add_argument("--idx", type=int, default=env.server_idx,
                    help="replica slot (env APUS_SERVER_IDX)")
    ap.add_argument("--config", default=env.config_path,
                    help="ClusterSpec JSON: peers, timing "
                         "(env APUS_CONFIG)")
    ap.add_argument("--join", action="store_true",
                    default=env.server_type == "join",
                    help="join a RUNNING cluster instead of starting as "
                         "a static member (env APUS_SERVER_TYPE=join); "
                         "--idx is ignored, the leader assigns the slot")
    ap.add_argument("--seed", default=os.environ.get("APUS_SEED"),
                    help="discovery bootstrap (implies --join): ONE "
                         "host:port of ANY live member — no config file "
                         "needed; the admission reply carries the peer "
                         "table and cluster spec (the mcast-JOIN "
                         "analog, dare_ibv_ud.c:952-1068).  Comma-"
                         "separate for multiple seeds")
    ap.add_argument("--join-addr", default=None,
                    help="with --join: bind this host:port instead of an "
                         "ephemeral one (a recovered server re-joining "
                         "at its original endpoint)")
    ap.add_argument("--want-slot", type=int, default=None,
                    help="with --join: slot affinity — admit at exactly "
                         "this slot or keep retrying (recovered-server "
                         "rejoin; identity is keyed by slot)")
    ap.add_argument("--db-dir", default=os.environ.get("APUS_DB_DIR"),
                    help="durable-store directory (restart recovery)")
    ap.add_argument("--log-file", default=env.log_file,
                    help="daemon log (env APUS_LOG_FILE)")
    ap.add_argument("--workdir", default=os.environ.get("APUS_WORKDIR"),
                    help="bridge shm/socket dir; enables the app bridge")
    ap.add_argument("--app", default=os.environ.get("APUS_APP"),
                    help="app argv to launch under interpose.so (port "
                         "appended, run.sh style); requires --workdir")
    ap.add_argument("--app-port", type=int,
                    default=int(os.environ.get("APUS_APP_PORT", "0")) or None)
    ap.add_argument("--serve-port", type=int,
                    default=int(os.environ.get("APUS_SERVE_PORT",
                                               "-1")),
                    help="protocol-aware app serving gateway "
                         "(runtime/serve.py): listen for RESP/"
                         "memcached-text app clients on this port and "
                         "serve the mapped GET/SET command set from "
                         "the replicated KVS (group router + follower "
                         "leases), with the interposed app as the "
                         "opaque-relay fallback when --app runs.  0 = "
                         "ephemeral (reported in the ready record); "
                         "-1/unset = disabled (env APUS_SERVE_PORT)")
    ap.add_argument("--spin-timeout-ms", type=int, default=8000)
    ap.add_argument("--tick-interval", type=float, default=0.0005)
    ap.add_argument("--ready-file", default=None,
                    help="write a JSON readiness record here once serving")
    ap.add_argument("--no-device-plane", action="store_true",
                    default=os.environ.get("APUS_DEVICE_PLANE") == "0",
                    help="run TCP-only even when the config enables the "
                         "multi-controller mesh plane")
    args = ap.parse_args(argv)

    bridged = args.workdir is not None
    if args.app and not bridged:
        ap.error("--app requires --workdir (the bridge's unix socket, "
                 "shm block, and record dump live there)")
    if args.seed:
        args.join = True
    if args.config:
        spec = load_config(args.config)
    elif args.seed:
        # Seed bootstrap: everything else arrives in the admission
        # reply (peer table + cluster spec).
        from apus_tpu.utils.config import ClusterSpec
        spec = ClusterSpec(peers=[])
    else:
        ap.error("need --config, or --seed for discovery bootstrap")
    if bridged and args.app and args.app_port is None:
        from apus_tpu.runtime.appcluster import free_port
        args.app_port = free_port()

    def make_sm(replica_idx):
        """Relay SM with a PER-REPLICA on-disk record dump (several
        daemons on one host share --workdir, like proxy{idx}.log)."""
        if not bridged:
            return None
        from apus_tpu.runtime.bridge import RelayStateMachine
        return RelayStateMachine(spill_path=os.path.join(
            args.workdir, f"records{replica_idx}.bin"))

    missing_groups: list = []
    join_my_addr = None
    if args.join:
        import socket as _socket

        from apus_tpu.parallel.net import PeerServer
        from apus_tpu.runtime.membership import request_join_spec
        from apus_tpu.utils.config import ClusterSpec
        if args.join_addr:
            host, port_s = args.join_addr.rsplit(":", 1)
            sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            sock.bind((host, int(port_s)))
        else:
            sock = PeerServer.reserve()
        host, port = sock.getsockname()
        my_addr = f"{host}:{port}"
        seeds = ([s.strip() for s in args.seed.split(",") if s.strip()]
                 if args.seed else [p for p in spec.peers if p])
        slot, cid, peers, spec_dict = request_join_spec(
            seeds, my_addr, want_slot=args.want_slot)
        if spec_dict is not None:
            # Adopt the CLUSTER's spec (timing envelope etc.) — a
            # seed-bootstrapped joiner has no config of its own, and a
            # config-bearing one must not run a different envelope than
            # the group.
            spec = ClusterSpec.from_dict(spec_dict)
        spec.peers = list(peers)
        while len(spec.peers) <= slot:
            spec.peers.append("")
        spec.peers[slot] = my_addr
        # Multi-group: a joiner is admitted into EVERY consensus group
        # (slots agree across groups; each group's leader answers its
        # own join).  The per-group cids seed the GroupSet's
        # incarnations so extra-group ctrl writes clear the removed-
        # slot fences immediately.
        group_cids = None
        missing_groups = []
        live_groups = None
        if getattr(spec, "groups", 1) > 1:
            from apus_tpu.runtime.client import probe_status
            from apus_tpu.runtime.membership import \
                request_join_all_groups
            # Elastic groups: a split may have grown the group count
            # past the static config — learn the LIVE count from any
            # member so the joiner enters every group that exists.
            live_groups = spec.groups
            for p in spec.peers:
                if not p or p == my_addr:
                    continue
                st = probe_status(p, timeout=1.0)
                if st is not None:
                    live_groups = max(live_groups,
                                      st.get("n_groups", 1))
                    break
            group_cids = request_join_all_groups(
                [p for i, p in enumerate(spec.peers)
                 if p and i != slot], my_addr, slot, live_groups)
            missing_groups = sorted(set(range(1, live_groups))
                                    - set(group_cids))
        join_my_addr = my_addr
        # Mesh-capable joiners carry a DETACHED runner: the leader's
        # reformer re-admits the slot into the device clique at the
        # next plane epoch (the RC re-handshake-on-rejoin analog).
        mesh_runner = _make_mesh_runner(args, spec, slot, joined=True)
        if mesh_runner is not None:
            mesh_runner.start()
        daemon = ReplicaDaemon(slot, spec, sm=make_sm(slot), cid=cid,
                               listen_sock=sock, recovery_start=True,
                               tick_interval=args.tick_interval,
                               log_file=args.log_file, db_dir=args.db_dir,
                               device_runner=mesh_runner,
                               group_cids=group_cids,
                               live_groups=live_groups)
    else:
        # Multi-controller mesh plane (runtime.mesh_plane): static
        # members 0..mesh_n-1 each own one device of the global mesh.
        # The build (jax.distributed rendezvous + compile) runs in the
        # background; TCP consensus serves immediately and the driver
        # engages once the plane is ready.  A restarted incarnation
        # starts DETACHED (the per-epoch incarnation rule) and rejoins
        # at the next plane epoch the leader's reformer assigns —
        # re-formation replaces the old "degraded until cluster
        # restart" semantics (RC re-handshake analog,
        # dare_ibv_ud.c:1098-1416).  Joiners beyond mesh_n stay
        # TCP-only: the device-capable slot set is fixed at cluster
        # launch, like a TPU slice's chip count.
        mesh_runner = _make_mesh_runner(args, spec, args.idx,
                                        joined=False)
        if mesh_runner is not None:
            mesh_runner.start()
        daemon = ReplicaDaemon(args.idx, spec, sm=make_sm(args.idx),
                               tick_interval=args.tick_interval,
                               log_file=args.log_file, db_dir=args.db_dir,
                               device_runner=mesh_runner,
                               recovery_start=bool(
                                   args.db_dir
                                   and daemon_store_exists(args.db_dir,
                                                           args.idx)))

    bridge = None
    app_proc = None
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    daemon.start()
    if missing_groups and join_my_addr:
        # Extra groups whose admission timed out at boot (mid-election/
        # mid-resize churn): finish them in the background.
        daemon.retry_group_joins(join_my_addr, missing_groups)
    # Re-formation orchestrator (active only while this daemon leads):
    # rebuilds the device clique under the next plane epoch once
    # membership re-stabilizes after a death/rejoin.
    reformer = None
    if getattr(daemon, "device_driver", None) is not None and \
            hasattr(daemon.device_driver.runner, "request_reform"):
        from apus_tpu.runtime.mesh_plane import MeshReformer
        reformer = MeshReformer(daemon, daemon.device_driver.runner, spec)
        reformer.start()
    app_server = None
    try:
        if bridged:
            from apus_tpu.runtime.bridge import Bridge, proxy_env
            bridge = Bridge(daemon, args.workdir, app_port=args.app_port)
            bridge.start()
            if args.app:
                app_argv = shlex.split(args.app) + [str(args.app_port)]
                app_env = dict(os.environ)
                app_env.update(proxy_env(
                    bridge,
                    log_path=os.path.join(args.workdir,
                                          f"proxy{daemon.idx}.log"),
                    spin_timeout_ms=args.spin_timeout_ms))
                app_proc = subprocess.Popen(app_argv, env=app_env)
        if args.serve_port is not None and args.serve_port >= 0:
            from apus_tpu.runtime.serve import AppServer
            app_server = AppServer(
                [p for p in spec.peers if p],
                port=args.serve_port,
                groups=getattr(spec, "groups", 1),
                fallback=(("127.0.0.1", args.app_port)
                          if bridged and args.app else None),
                stats=(daemon.obs.view("srv")
                       if daemon.obs is not None else None),
                logger=daemon.logger)
            app_server.start()
            daemon.logger.info("app serving gateway on %s:%d",
                               *app_server.addr)

        addr = f"{daemon.server.addr[0]}:{daemon.server.addr[1]}"
        ready = {"idx": daemon.idx, "addr": addr, "pid": os.getpid(),
                 "app_port": args.app_port if bridged else None,
                 "serve_port": (app_server.addr[1]
                                if app_server is not None else None)}
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                _json.dump(ready, f)
            os.replace(tmp, args.ready_file)
        print(f"APUS-READY {_json.dumps(ready)}", flush=True)

        # Removal self-detection (DARE recovery semantics): a replica
        # that the failure detector removed while it was down/partitioned
        # receives nothing ever again — PreVote keeps it from even
        # bumping its term.  If our state makes no progress while some
        # peer IS a leader whose membership excludes us, re-enter the
        # group through the join protocol at our own endpoint.
        last_progress = None
        start_t = progress_t = time.monotonic()
        last_probe = 0.0
        heard_leader = False
        # Orphan watchdog (harness-launched daemons only): a test or
        # benchmark harness killed by a timeout never runs
        # ProcCluster.stop(), and its replicas — in their own process
        # groups by design — would run forever, thrashing evict/rejoin
        # cycles and starving every later harness on the box (observed:
        # a timeout-killed mesh bench left a 3-replica cluster churning
        # for 9+ minutes, failing a concurrent soak's election probe).
        # The env var carries the HARNESS pid (not a boolean): capturing
        # getppid() here instead would race startup — a harness that
        # dies while this daemon is still in daemon.start() has already
        # reparented us, and we would record the reaper's pid and never
        # fire.  Comparing against the spawn-time harness pid detects
        # that window too.  Unset (or unparseable/non-positive) =
        # disabled, so manually-launched daemons whose shell
        # legitimately exits are unaffected.
        try:
            harness_pid = int(os.environ.get("APUS_EXIT_IF_ORPHANED", ""))
        except ValueError:
            harness_pid = 0
        while not stop_evt.is_set():
            if daemon.draining:
                # Graceful leave (OP_LEAVE): our removal is committed
                # cluster-wide.  Give in-flight handler replies a
                # beat, then exit CLEAN (rc 0) — the "drained replica
                # exits clean" contract, vs. eviction's rejoin loop.
                stop_evt.wait(0.5)
                daemon.logger.info("drained (graceful leave); exiting")
                return 0
            if harness_pid > 0 and os.getppid() != harness_pid:
                daemon.logger.error(
                    "harness (pid %d) gone; exiting "
                    "(APUS_EXIT_IF_ORPHANED)", harness_pid)
                return 0
            if app_proc is not None and app_proc.poll() is not None:
                daemon.logger.error("app exited rc=%d; shutting down",
                                    app_proc.returncode)
                return 1
            now = time.monotonic()
            with daemon.lock:
                progress = (daemon.node.current_term, daemon.node.log.commit,
                            daemon.node.is_leader)
                # _last_hb_seen lives in the daemon-clock domain.
                hb_age = daemon.clock() - daemon.node._last_hb_seen
            if progress != last_progress:
                last_progress, progress_t = progress, now
            with daemon.lock:
                heard_leader = heard_leader or daemon.node.group_contact
            # "Stalled" keys off heartbeat age, not just state change:
            # an idle-but-led follower hears the leader every hb_period
            # and must never start probing peers.  BOOT is the urgent
            # case: a restarted evicted replica hears nothing from the
            # first tick, and every second before its rejoin is a
            # window in which one more failure stalls the whole group
            # (its slot still counts toward quorum_size) — so until a
            # leader has been heard at all, probe after 0.5 s.  The
            # steady-state re-exec threshold sits ABOVE the in-place
            # rejoin watchdog's silence window, so the cheap in-place
            # path always gets to act first.
            reexec_after = exclusion_silence(spec) + 1.5
            silent_boot = (not heard_leader and not progress[2]
                           and now - start_t > 0.5)
            stalled = (not progress[2] and now - progress_t > reexec_after
                       and hb_age > reexec_after)
            if (stalled or silent_boot) and now - last_probe > 0.5 \
                    and not daemon.draining:
                last_probe = now
                if _excluded_by_live_leader(daemon, spec):
                    daemon.logger.error(
                        "removed from the group (a live leader excludes "
                        "slot %d); re-joining at %s", daemon.idx,
                        spec.peers[daemon.idx])
                    my_addr = spec.peers[daemon.idx]
                    # Full teardown, then re-exec in join mode at the
                    # same endpoint (the recovered-server path).
                    _stop_app(app_proc)
                    app_proc = None
                    if bridge is not None:
                        bridge.stop()
                        bridge = None
                    daemon.stop()
                    rejoin = [sys.executable, "-m",
                              "apus_tpu.runtime.daemon",
                              "--join", "--join-addr", my_addr,
                              "--want-slot", str(daemon.idx)]
                    if not args.config:
                        # Seed-bootstrapped daemon: re-seed from the
                        # peers learned via the admission reply.
                        rejoin += ["--seed", ",".join(
                            p for i, p in enumerate(spec.peers)
                            if p and i != daemon.idx)]
                    for flag, val in [
                            ("--config", args.config),
                            ("--db-dir", args.db_dir),
                            ("--log-file", args.log_file),
                            ("--workdir", args.workdir),
                            ("--app", args.app),
                            ("--ready-file", args.ready_file)]:
                        if val:
                            rejoin += [flag, val]
                    if args.app_port:
                        rejoin += ["--app-port", str(args.app_port)]
                    if args.serve_port is not None \
                            and args.serve_port >= 0:
                        rejoin += ["--serve-port",
                                   str(args.serve_port)]
                    rejoin += ["--spin-timeout-ms",
                               str(args.spin_timeout_ms),
                               "--tick-interval", str(args.tick_interval)]
                    os.execv(sys.executable, rejoin)
            stop_evt.wait(0.2)
        return 0
    finally:
        if reformer is not None:
            reformer.stop()
        if app_server is not None:
            app_server.stop()
        _stop_app(app_proc)
        if bridge is not None:
            bridge.stop()
        daemon.stop()


def _stop_app(app_proc) -> None:
    import subprocess
    if app_proc is not None and app_proc.poll() is None:
        app_proc.terminate()
        try:
            app_proc.wait(timeout=3.0)
        except subprocess.TimeoutExpired:
            app_proc.kill()


def daemon_store_exists(db_dir: str, idx: int) -> bool:
    import os

    from apus_tpu.runtime.persist import daemon_store_path
    return os.path.exists(daemon_store_path(db_dir, idx))


def _mesh_marker_path(args, spec, idx: int):
    import os
    mdir = args.db_dir or args.workdir or (
        os.path.dirname(args.ready_file) if args.ready_file else None)
    if mdir is None:
        return None          # nowhere to remember: best effort
    os.makedirs(mdir, exist_ok=True)
    return os.path.join(mdir, f"mesh-incarnation-{idx}")


def _mesh_marker_read(args, spec, idx: int):
    """Mesh membership is PER-INCARNATION-PER-EPOCH: a crashed-and-
    restarted replica must NOT reconnect to a coordination-service
    instance its dead incarnation was part of — the service rejects the
    new incarnation (ABORTED) and the runtime's error polling then
    LOG(FATAL)-terminates every HEALTHY member (observed empirically),
    turning a routine restart into a total outage.  The durable marker
    records (coordinator address, last epoch this slot joined).
    Returns that epoch when the marker matches the current coordinator
    — the restarted daemon then starts DETACHED and only participates
    from epoch+1 on (assigned by the leader's reformer) — or None for
    a fresh slot / a new coordinator (whole-cluster restart)."""
    marker = _mesh_marker_path(args, spec, idx)
    if marker is None:
        return None
    try:
        with open(marker) as f:
            lines = f.read().splitlines()
        if lines and lines[0].strip() == spec.mesh_coordinator:
            return int(lines[1]) if len(lines) > 1 else 0
    except (OSError, ValueError):
        pass
    return None


def _mesh_marker_write(args, spec, idx: int, epoch: int) -> None:
    """Record "this incarnation joined plane epoch E" BEFORE connecting
    to E's coordination service (MeshCommitRunner.on_epoch_join)."""
    import os
    marker = _mesh_marker_path(args, spec, idx)
    if marker is None:
        return
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{spec.mesh_coordinator}\n{epoch}\n")
    os.replace(tmp, marker)


def _make_mesh_runner(args, spec, idx: int, joined: bool):
    """Mesh runner for slot ``idx`` when the config enables the
    multi-controller plane and the slot is mesh-capable; None
    otherwise.  ``joined=True`` (join-protocol entry — a recovered or
    fresh member admitted by the leader) always starts DETACHED: this
    incarnation may never re-enter an epoch an earlier incarnation of
    the slot was part of, so it waits for the leader's reformer to
    assign the next one."""
    if not (spec.mesh_coordinator and spec.mesh_n > 0
            and 0 <= idx < spec.mesh_n and not args.no_device_plane):
        return None
    from apus_tpu.runtime.mesh_plane import MeshCommitRunner
    from apus_tpu.utils.debug import make_logger
    detached_epoch = _mesh_marker_read(args, spec, idx)
    if joined and detached_epoch is None:
        detached_epoch = -1             # fresh joiner: detached, no past
    runner = MeshCommitRunner(
        spec, idx,
        logger=make_logger(f"apus.mesh{idx}", args.log_file),
        detached_epoch=detached_epoch)
    runner.on_epoch_join = \
        lambda e: _mesh_marker_write(args, spec, idx, e)
    return runner


def _excluded_by_live_leader(daemon: "ReplicaDaemon", spec) -> bool:
    """True iff some reachable peer is a leader (at a term >= ours)
    whose membership does NOT contain our slot — the affirmative signal
    that the failure detector removed us.  A mere partition (no leader
    reachable, or a leader that still lists us) never triggers.

    Probes FOLLOW leader hints: the current leader may be a replica
    that joined after our boot config was written (an elastic/churn
    cluster grows), so a followers-only peer table must still find it
    through their ``leader_addr`` answers — without the hop, a victim
    restarted while a joiner led sat unexcluded-looking forever (the
    wedge the first elastic campaign caught)."""
    from apus_tpu.runtime.client import probe_status
    my_addr = spec.peers[daemon.idx] if daemon.idx < len(spec.peers) else ""
    seen: set = set()
    queue = [a for a in spec.peers if a and a != my_addr]
    while queue:
        addr = queue.pop(0)
        if addr in seen:
            continue
        seen.add(addr)
        st = probe_status(addr, timeout=0.3)
        if st is None:
            continue
        if (st.get("is_leader")
                and st.get("term", 0) >= daemon.node.current_term
                and daemon.idx not in st.get("members", [])):
            return True
        la = st.get("leader_addr")
        if la and la != my_addr and la not in seen:
            queue.append(la)
    return False


if __name__ == "__main__":
    import sys
    sys.exit(main())
