"""ReplicaDaemon: one live replica — protocol thread + peer server.

The reference runs consensus as a thread inside the application process
(proxy.c:76-81 -> dare_server_init -> ev_run, dare_server.c:173-238).
Our TPU-era split keeps the application untouched and runs consensus in a
separate daemon process per replica; the native proxy talks to it over a
unix socket + shared-memory commit counter (apus_tpu.runtime.bridge).

The daemon owns:
- the pure protocol ``Node`` (apus_tpu.core.node), ticked by a dedicated
  thread at sub-millisecond cadence (the libev loop analog,
  dare_server.c:216-238);
- a ``PeerServer`` exposing its regions/log to peers (the registered MRs);
- a ``NetTransport`` for its own one-sided ops to peers (the QPs);
- committed-entry upcalls: persistence + replay/release callbacks (the
  proxy callback table analog, dare_sm.h:42-47).

Thread-safety: a single RLock guards the node.  The tick thread holds it
for each tick but the transport releases it while blocked on the wire
(see apus_tpu.parallel.net docstring); peer-server handlers and client
submits take it for their short critical sections.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from apus_tpu.core.cid import Cid
from apus_tpu.core.log import LogEntry
from apus_tpu.core.node import Node, NodeConfig, PendingRequest
from apus_tpu.models.sm import StateMachine
from apus_tpu.models.kvs import KvsStateMachine
from apus_tpu.parallel.net import NetTransport, PeerServer
from apus_tpu.utils.config import ClusterSpec
from apus_tpu.utils.debug import make_logger


def _parse_peer(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


class ReplicaDaemon:
    """One replica of the group, live on the network."""

    def __init__(self, idx: int, spec: ClusterSpec,
                 sm: Optional[StateMachine] = None,
                 cid: Optional[Cid] = None,
                 listen_sock=None,
                 tick_interval: float = 0.0005,
                 log_file: Optional[str] = None,
                 db_dir: Optional[str] = None,
                 recovery_start: bool = False,
                 seed: int = 0,
                 device_runner=None):
        self.idx = idx
        self.spec = spec
        self.lock = threading.RLock()
        self.logger = make_logger(f"apus.srv{idx}", log_file)
        self._tick_interval = tick_interval

        peers = {i: _parse_peer(a) for i, a in enumerate(spec.peers)}
        self.transport = NetTransport(peers, yield_lock=self.lock)
        cfg = NodeConfig(
            idx=idx, n_slots=spec.n_slots, hb_period=spec.hb_period,
            hb_timeout=spec.hb_timeout, elect_low=spec.elect_low,
            elect_high=spec.elect_high, prune_period=spec.prune_period,
            max_batch=spec.max_batch, auto_remove=spec.auto_remove,
            fail_window=spec.fail_window, recovery_start=recovery_start,
            seed=seed)
        self.node = Node(cfg, cid or Cid.initial(spec.group_size),
                         sm or KvsStateMachine(), self.transport)
        # Fresh-start grace: randomize the first election timeout so a
        # cold cluster elects cleanly (dare_server.c:1237).
        self.node._last_hb_seen = (time.monotonic()
                                   + self.node.rng.random()
                                   * self.node.cfg.elect_high)

        host, port = peers.get(idx, ("127.0.0.1", 0))
        self.server = PeerServer(lambda: self.node, self.lock,
                                 host=host, port=port, sock=listen_sock,
                                 extra_ops=self._extra_ops(),
                                 logger=self.logger)

        # Committed-entry observers (proxy callback table analog):
        # each gets (LogEntry); registered by persistence/replay layers.
        self.on_commit: list[Callable[[LogEntry], None]] = []
        # Per-tick observers, called under the node lock after upcalls —
        # used by the bridge to mirror role/term into shared memory
        # synchronously with role transitions (no stale-flag window).
        self.on_tick: list[Callable[[], None]] = []
        # Snapshot-install observers: (Snapshot, ep_dump) after a
        # leader-pushed snapshot replaced local state (persistence must
        # record it; a proxied replica's bridge re-primes its app).
        self.on_snapshot: list[Callable] = []

        # Durable store (stable storage, db-interface.c analog).  On
        # restart with an existing store, replay it into the SM and
        # endpoint DB first: catch-up re-replication then hits the
        # apply-time dedup, so commands are neither re-executed nor
        # re-persisted (the reference replays its BDB dump the same way,
        # proxy.c:306-339).
        self.persistence = None
        if db_dir is not None:
            from apus_tpu.runtime.persist import (Persistence,
                                                  daemon_store_path)
            self.persistence = Persistence(daemon_store_path(db_dir, idx))
            if self.persistence.store.count:
                self.persistence.replay_into(self.node.sm, self.node.epdb)
            self.on_commit.append(self.persistence.on_commit)
            self.on_snapshot.append(self.persistence.on_snapshot)

        # Device plane (runtime.device_plane): the jitted commit step as
        # the primary replication/quorum engine, host TCP as control
        # plane + catch-up (the RC-data/UD-control split of the
        # reference, SURVEY §5.8).
        self.device_driver = None
        if device_runner is not None:
            from apus_tpu.runtime.device_plane import DevicePlaneDriver
            self.device_driver = DevicePlaneDriver(self, device_runner)

        self._stop = threading.Event()
        self._tick_thread: Optional[threading.Thread] = None
        self._last_role = None
        # Client-facing handlers wait on this instead of polling the
        # lock (K pollers at 0.2 ms would starve the tick thread).
        self.commit_cond = threading.Condition(self.lock)

    # -- extra (two-sided) control ops ------------------------------------

    #: how long a client-facing handler blocks waiting for commit/apply
    client_op_timeout: float = 5.0

    def _extra_ops(self) -> dict:
        from apus_tpu.runtime.client import make_client_ops
        from apus_tpu.runtime.membership import make_membership_ops
        return {**make_client_ops(self), **make_membership_ops(self)}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        t = threading.Thread(target=self._run, name=f"apus-tick-{self.idx}",
                             daemon=True)
        t.start()
        self._tick_thread = t
        if self.device_driver is not None:
            self.device_driver.start()
        self.logger.info("daemon %d up at %s", self.idx, self.server.addr)

    def stop(self) -> None:
        self._stop.set()
        if self.device_driver is not None:
            self.device_driver.stop()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=2.0)
        self.server.stop()
        self.transport.close()
        if self.persistence is not None:
            self.persistence.close()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                with self.lock:
                    self.node.tick(time.monotonic())
                    self._drain_upcalls()
                    self._log_role_changes()
                    for cb in self.on_tick:
                        cb()
                    self.commit_cond.notify_all()
            except Exception:
                # A tick must never silently kill the replica (a dead
                # tick thread with a live PeerServer is a zombie that
                # still acks writes).  Log and keep ticking; persistent
                # faults will surface via the failure detector.
                self.logger.exception("tick failed")
            time.sleep(self._tick_interval)

    def _drain_upcalls(self) -> None:
        if self.node.snapshot_upcalls:
            snaps, self.node.snapshot_upcalls = \
                self.node.snapshot_upcalls, []
            for snap, ep_dump in snaps:
                for cb in self.on_snapshot:
                    cb(snap, ep_dump)
        if self.node.config_upcalls:
            cfgs, self.node.config_upcalls = self.node.config_upcalls, []
            for e in cfgs:
                self._handle_config_entry(e)
        if not self.node.committed_upcalls:
            return
        entries, self.node.committed_upcalls = \
            self.node.committed_upcalls, []
        for e in entries:
            for cb in self.on_commit:
                cb(e)

    def _handle_config_entry(self, e: LogEntry) -> None:
        """Applied CONFIG entry: learn new peers (the poll_config_entries
        follower side, dare_server.c:2133-2187).  Join entries carry
        ``"<slot> <addr>"`` in data."""
        if e.data:
            try:
                slot_s, addr = e.data.decode().split(" ", 1)
                slot = int(slot_s)
            except ValueError:
                self.logger.warning("bad CONFIG payload %r", e.data)
                return
            if slot != self.idx:
                self.transport.set_peer(slot, _parse_peer(addr))
            # Shared-spec peer table: idempotent slot-indexed write (all
            # daemons of a LocalCluster share one spec object).
            peers = self.spec.peers
            while len(peers) <= slot:
                peers.append("")
            peers[slot] = addr
            self.logger.info("CONFIG: slot %d -> %s (%r)", slot, addr,
                             e.cid)

    def _log_role_changes(self) -> None:
        role = (self.node.role, self.node.current_term)
        if role != self._last_role:
            self._last_role = role
            # Leader banner greppable by ops tooling, matching the
            # "[T<term>] LEADER" lines run.sh greps (run.sh:46-68).
            if self.node.is_leader:
                self.logger.info("[T%d] LEADER", self.node.current_term)
            else:
                self.logger.info("[T%d] %s", self.node.current_term,
                                 self.node.role.name)

    # -- client-facing API ------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.node.is_leader

    @property
    def term(self) -> int:
        return self.node.current_term

    @property
    def leader_hint(self) -> Optional[int]:
        return self.node.leader_hint

    def submit(self, req_id: int, clt_id: int,
               data: bytes) -> Optional[PendingRequest]:
        with self.lock:
            return self.node.submit(req_id, clt_id, data)

    def wait_committed(self, pr: PendingRequest,
                       timeout: float = 5.0) -> bool:
        """Block until the request is applied (the proxy release analog,
        proxy_update_state proxy.c:263-267).  Success is gated on the
        reply sentinel — commit/apply position alone can be satisfied by
        a DIFFERENT entry after a truncation."""
        deadline = time.monotonic() + timeout
        with self.commit_cond:
            while True:
                if pr.reply is not None:
                    return True
                if not self.node.is_leader:
                    return False      # lost leadership: client must retry
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self.commit_cond.wait(min(left, 0.05))
