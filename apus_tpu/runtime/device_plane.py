"""Device plane wired into the live runtime.

The reference's one-sided data plane runs INSIDE the commit loop —
``rc_write_remote_logs`` is called from ``commit_new_entries`` on the
leader's hot path (dare_server.c:1751-1763 -> dare_ibv_rc.c:1870-1948) —
while everything asymmetric/asynchronous (election, join, heartbeats)
rides the UD control plane.  This module gives the live runtime the same
split: the jitted commit step (apus_tpu.ops.commit) becomes the primary
replication + quorum engine, and the host TCP plane
(apus_tpu.parallel.net) remains control plane + divergence repair +
catch-up.

Components:

- ``DeviceCommitRunner`` — one per process (shared by every in-process
  replica daemon, the way one TPU mesh is shared by the replica shards
  it hosts).  Owns the HBM ``DeviceLog`` (leading replica axis, sharded
  over the mesh), the compiled commit step, and the round cursor.  The
  leader's driver feeds it batches; follower drivers read their own
  shard back out of it.

- ``DevicePlaneDriver`` — one thread per daemon.
  Leader half: pad the host log to a batch boundary, ship each aligned
  64-entry span through the jitted step (leader->all pmax scatter,
  fence mask, psum quorum — one XLA program), and advance the host
  ``log.commit`` from the device quorum result; once the device plane
  covers everything past its base index, the host ack-quorum rule is
  switched off (``node.external_commit``) so commit decisions are owned
  by the device plane, exactly as the reference's commit is owned by
  the RDMA ack scan (dare_ibv_rc.c:1650-1758).
  Follower half: drain committed-round rows from the local replica's
  device shard into the host log (the device plane IS the entry
  transport; TCP merely repairs divergence and carries the commit
  offset, mirroring the reference's lazily-written remote commit,
  dare_ibv_rc.c:1760-1826).

Safety arguments (the seams that matter):

1. *Commit chaining.*  Device quorum for a round attests replication of
   ``[dev_base, end0+B)`` across the replica shards — nothing below
   ``dev_base`` (shards are reset empty at each leadership change).  The
   leader therefore only adopts device commit results once its host
   commit has reached ``dev_base`` through the ordinary host ack quorum;
   from then on every advance is prefix-complete.
2. *Follower drain.*  A follower appends device rows only when its last
   host-log entry carries the CURRENT leader's term: by the Raft log-
   matching property that entry pins the whole prefix to the leader's
   log, so building on it cannot graft new entries onto a diverged tail.
   (The leader guarantees a term-T entry exists below ``dev_base``: the
   become_leader blank entry, plus any alignment padding, are appended
   at term T before the device base is chosen.)  Followers never advance
   commit from the device arrays — the commit offset arrives via the
   leader's TCP writes, which already encode the gating of (1).
3. *Live-mask honesty.*  In-process, a crashed daemon's device shard
   still accepts scatters (the arrays outlive the thread), so device
   acks alone would count the dead.  The driver masks the quorum vote to
   members whose host control-plane writes (REP_ACK) were observed
   within a failure-detection window — the quorum *denominator* stays
   ``quorum_size(cid)``, so masking can only make commit harder, never
   easier.  This matches the reference's window: RDMA acks are also
   trusted until QP retry exhaustion flags the peer.

Oversized records (> slot width; none once core.segment is enabled) make
a round device-ineligible: the driver falls back to host-path commit for
that span and re-bases the device plane past it.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from apus_tpu.core.log import LogEntry
from apus_tpu.core.quorum import quorum_size
from apus_tpu.core.types import EntryType
from apus_tpu.parallel import wire
from apus_tpu.parallel.transport import Region

# -- process-wide XLA compile accounting (the recompile sentinel's
#    signal source).  jax.monitoring fires one
#    /jax/core/compile/backend_compile_duration event per REAL backend
#    compile (cached dispatches fire nothing; the C++ fastpath cache
#    can grow per call signature WITHOUT compiling, so jit cache sizes
#    alone over-report).  Builders account their own compiles into
#    _EXPECTED, so "unexpected compiles" — the PR 3 mid-leadership
#    stall class — is (total - expected), stable across other runners
#    building in the same process.
_COMPILES = {"count": 0, "secs": 0.0}
_EXPECTED = {"count": 0}
_LISTENING = [False]


def _ensure_compile_listener() -> None:
    if _LISTENING[0]:
        return
    try:
        from jax import monitoring

        def _on_event(name: str, secs: float, **_kw) -> None:
            if name == "/jax/core/compile/backend_compile_duration":
                _COMPILES["count"] += 1
                _COMPILES["secs"] += secs

        monitoring.register_event_duration_secs_listener(_on_event)
        _LISTENING[0] = True
    except Exception:                                 # noqa: BLE001
        pass          # sentinel degrades to "never fires", not a crash


def unexpected_compiles() -> int:
    """Backend compiles nobody's build/warmup accounted for."""
    return _COMPILES["count"] - _EXPECTED["count"]


class DeviceCommitRunner:
    """Process-wide device-plane engine: HBM log shards + jitted commit
    step, shared by all in-process replica daemons."""

    #: Rounds per pipelined dispatch (commit_rounds): one lax.scan
    #: program covering PIPE_DEPTH consecutive rounds, used by the
    #: driver when the backlog allows.
    PIPE_DEPTH = 4
    #: Rounds per base DEEP dispatch, used when the backlog covers
    #: DEEP_DEPTH full batches.  On an accelerator this rung runs the
    #: fused closed-form window step (build_pipelined_commit_step_fused,
    #: whose ring-rewrite cost is invisible next to dispatch latency);
    #: on the CPU backend it runs the scan step at the same depth —
    #: see the builder selection in _build_locked.  DEEP_DEPTH is also
    #: the unit of the follower drain's bulk gather (read_rows window).
    DEEP_DEPTH = 16
    #: Backlog-adaptive deep ladder (accelerator backends only): the
    #: driver dispatches the DEEPEST rung the host backlog covers, so a
    #: tunnel/dispatch-latency-dominated deployment amortizes one
    #: dispatch over up to 256 rounds — the live-path counterpart of
    #: the bench's depth ladder, and the reference's "keep the NIC
    #: queue full" discipline (dare_ibv_rc.c:2552-2568).  On the CPU
    #: backend the ladder stays at (DEEP_DEPTH,): there is no dispatch
    #: round trip worth amortizing, and each extra rung costs a
    #: compile in every runner build (the test suite builds many).
    DEEP_DEPTHS = (16, 64, 256)

    def __init__(self, n_replicas: int, n_slots: int = 4096,
                 slot_bytes: int = 4096, batch: int = 64,
                 devices=None, logger=None):
        self.n_replicas = n_replicas
        self.n_slots = n_slots
        self.slot_bytes = slot_bytes
        self.batch = batch
        self._devices = devices
        self.logger = logger
        self.lock = threading.Lock()
        self._build_lock = threading.Lock()
        self.generation = 0               # bumped by every reset()
        self._devlog = None
        self._next_end0: Optional[int] = None
        self._leader: Optional[int] = None
        self._term = 0
        self._built = False
        # Device-plane telemetry rides a registry of its own (the
        # runner is process-wide, shared by every in-process daemon;
        # OP_METRICS/OP_OBS_DUMP merge this snapshot into each
        # replica's scrape) — the ad-hoc stats dict becomes the
        # dict-compatible dev_* view over it, so every legacy
        # ``runner.stats[...]`` consumer keeps working while the
        # counters/gauges/histograms become scrapeable.
        from apus_tpu.obs.metrics import MetricsRegistry
        self.metrics = MetricsRegistry()
        self.stats = self.metrics.view("dev")
        for k in ("rounds", "resets", "quorum_fail_rounds",
                  "entries_devplane", "pipelined_dispatches",
                  "window_dispatches", "deep_dispatches",
                  "early_exits", "recompiles"):
            self.stats.setdefault(k, 0)
        #: slowest blocked device-result wait observed (the stall
        #: watchdog scales to this) — a float gauge behind the same
        #: "max_dispatch_ms" view key the dict exposed.
        self._max_dispatch = self.metrics.gauge("dev_max_dispatch_ms")
        self._dispatch_wait_hist = self.metrics.histogram(
            "dev_dispatch_wait_us")
        self._window_wall_hist = self.metrics.histogram(
            "dev_window_wall_us")
        self._window_depth_hist = self.metrics.histogram(
            "dev_window_depth")
        self._rounds_run_hist = self.metrics.histogram(
            "dev_window_rounds_run")
        #: post-warmup compile-cache baseline per live executable
        #: (attribution hints) + the unexpected-compile watermark the
        #: sentinel actually alarms on; armed at the end of _build.
        self._exec_cache_sizes: Optional[dict] = None
        self._compile_baseline = 0
        #: dispatch-depth histogram {window_rounds: dispatches} — the
        #: wrl_count_array analog (the reference histograms its commit
        #: loop's iteration counts, dare_ibv_rc.c:1868-1937); this shows
        #: how often traffic rode the single/scan/deep window shapes.
        self.depth_histogram: dict[int, int] = {}
        # Build + compile eagerly: a lazy multi-second first compile
        # would hand the opening of every first leadership to the host
        # path (and leave the device cursor behind a pruned head).
        self._build()

    # -- lazy jax build ---------------------------------------------------

    def _build(self) -> None:
        with self._build_lock:
            self._build_locked()

    def _build_locked(self) -> None:
        if self._built:
            return
        # Every compile this build+warmup performs is EXPECTED: the
        # sentinel only alarms on compiles past this accounting.
        _ensure_compile_listener()
        _compiles_at_build_start = _COMPILES["count"]
        import jax

        from apus_tpu.ops.commit import build_commit_step
        from apus_tpu.ops.mesh import replica_mesh, replica_sharding

        devices = self._devices
        if devices is None:
            devices = jax.devices()[:1]   # single-chip fold by default
        self._mesh = replica_mesh(self.n_replicas, devices=devices)
        self._sharding = replica_sharding(self._mesh)
        self._step = build_commit_step(self._mesh, self.n_replicas,
                                       self.n_slots, self.slot_bytes,
                                       self.batch)
        # Follower drain fetch: exactly one batch of rows per call, so
        # the device->host transfer is B*SB bytes (a naive
        # ``np.asarray(devlog.data[r])`` would ship the whole 16 MB
        # shard per poll and starve the commit path).
        self._gather = jax.jit(lambda d, m, r, s: (d[r, s], m[r, s]))
        # One replica's offsets row, as a NEW buffer: shard_end must not
        # hand out a view of the (donated) devlog arrays.
        self._offs_one = jax.jit(lambda o, r: o[r])
        # Round-result packer: acks [R] + commit scalar fused into ONE
        # [R+1] array so the leader round blocks on a single
        # device->host transfer (two separate readbacks pay two relay
        # round trips on a tunneled chip).
        self._pack_result = jax.jit(
            lambda acks, commit: jnp.concatenate([acks, commit[None]]))
        # Leader-row expansion ON DEVICE: the host ships only the
        # leader's [B,SB] batch; the [R,B,SB] leader-row-only layout the
        # step consumes (zeros elsewhere) is built by XLA.  Staging a
        # host-side [R,B,SB] zeros array instead (ops.commit.place_batch)
        # costs ~1 MB of alloc+transfer of zeros per round — measured at
        # ~30% of the live round on the bench's live-runner phase.
        import jax.numpy as jnp

        R, B, SB = self.n_replicas, self.batch, self.slot_bytes

        def _expand(bd, bm, leader):
            # DYNAMIC leader index (one program for every leader): a
            # static leader would recompile on the first round of each
            # new leadership — a multi-second stall the driver's own
            # watchdog would misread as a wedged device plane.
            data = jnp.zeros((R, B, SB), jnp.uint8) \
                .at[leader].set(bd)
            meta = jnp.zeros((R, B, 4), jnp.int32) \
                .at[leader].set(bm)
            return data, meta

        self._place_dev = jax.jit(
            _expand, out_shardings=(self._sharding, self._sharding))
        # On the CPU backend there is no transfer to save and the
        # jitted zeros+scatter costs MORE than the plain host staging
        # (measured on the bench's live-runner phase) — keep the
        # host-side place_batch there.
        self._use_device_expand = jax.default_backend() != "cpu"

        def _place(bd, bm, leader):
            if self._use_device_expand:
                return self._place_dev(bd, bm, np.int32(leader))
            from apus_tpu.ops.commit import place_batch
            return place_batch(self._mesh, R, leader, bd, bm)

        self._place = _place

        # Pipelined dispatch: K consecutive rounds inside ONE XLA
        # program — the live form of the reference's many-outstanding-
        # WRs pipelining (post_send selective signaling,
        # dare_ibv_rc.c:2552-2568).  The driver uses it whenever the
        # host backlog covers K full batches, cutting dispatch+sync
        # overhead per round by ~K.
        from apus_tpu.ops.commit import (build_pipelined_commit_step,
                                         build_pipelined_commit_step_fused,
                                         build_windowed_commit_step)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from apus_tpu.ops.mesh import REPLICA_AXIS
        K = self.PIPE_DEPTH
        # SHALLOW windows (1..PIPE_DEPTH rounds) ride the single-window
        # latency engine: ONE compiled program with a runtime round
        # count and device-side early exit, donating both the devlog
        # and the CommitControl (vote-mask) buffers.  This replaces the
        # per-depth scan compile the old shallow rung paid, and lets a
        # depth-1 and a depth-4 window share one executable — the
        # un-amortized single-dispatch path the bench's --single-window
        # mode measures.
        self._window = build_windowed_commit_step(
            self._mesh, R, self.n_slots, SB, B, max_depth=K)
        # DEEP rungs stay per-depth programs: the fused closed-form
        # step on an accelerator (per-dispatch cost ~= one ring update,
        # invisible next to dispatch latency; the pallas in-place
        # kernel makes it proportional again) — but on the CPU backend
        # the fused ring rewrite costs ~25x the scan's proportional
        # writes at this depth, so CPU keeps the scan shape for the
        # deep rung (same rationale as _use_device_expand; the two
        # programs are differentially tested semantically identical).
        deep_builder = (build_pipelined_commit_step_fused
                        if jax.default_backend() != "cpu"
                        else build_pipelined_commit_step)
        deep_depths = (self.DEEP_DEPTHS if jax.default_backend() != "cpu"
                       else (self.DEEP_DEPTH,))
        self._pipes = {}
        for D in deep_depths:
            self._pipes[D] = deep_builder(
                self._mesh, R, self.n_slots, SB, B, depth=D,
                staged_depth=D)
        #: dispatchable window depths descending — the driver's
        #: window-selection order (deep pipes + the shallow engine's
        #: max; depths below PIPE_DEPTH ride the same engine with a
        #: smaller runtime round count).
        self.window_depths = sorted(set(self._pipes) | {K}, reverse=True)
        #: which ring-rewrite path each fused rung compiled to
        #: ('compiled' pallas / 'off' XLA select; None = scan/windowed
        #: step) — surfaced in bench detail so numbers are attributable.
        self.pallas_modes = {K: getattr(p, "pallas_mode", None)
                             for K, p in self._pipes.items()}
        self.pallas_modes.setdefault(K, None)
        staged_sh = NamedSharding(self._mesh, P(None, REPLICA_AXIS))
        self._staged_sharding = staged_sh

        def _expand_staged(bd, bm, leader):
            d = bd.shape[0]             # retraced per window depth
            data = jnp.zeros((d, R, B, SB), jnp.uint8) \
                .at[:, leader].set(bd)
            meta = jnp.zeros((d, R, B, 4), jnp.int32) \
                .at[:, leader].set(bm)
            return data, meta

        self._place_staged_dev = jax.jit(
            _expand_staged, out_shardings=(staged_sh, staged_sh))

        def _place_staged(bd, bm, leader):
            if self._use_device_expand:
                return self._place_staged_dev(bd, bm, np.int32(leader))
            d = bd.shape[0]
            data = np.zeros((d, R, B, SB), np.uint8)
            meta = np.zeros((d, R, B, 4), np.int32)
            data[:, leader] = bd
            meta[:, leader] = bm
            return (jax.device_put(data, staged_sh),
                    jax.device_put(meta, staged_sh))

        self._place_staged = _place_staged
        # Double-buffered reusable host staging (ops.logplane): window
        # encoding for dispatch N+1 overlaps the device's execution of
        # window N; acquire() blocks only on the consumer edge (the
        # transfer that read the buffer two windows ago).
        from apus_tpu.ops.logplane import HostStagingRing
        self._staging = HostStagingRing(B, SB)
        # Occupancy telemetry: how long window encoding blocks on the
        # consumer edge (the transfer that read this buffer pair two
        # windows ago) — nonzero p99 here means staging, not the
        # device, is the pipeline's wait.
        self._staging.wait_hist = self.metrics.histogram(
            "dev_staging_wait_us")
        #: Whether the driver keeps deep windows in flight
        #: (commit_rounds_async) rather than resolving each before
        #: staging the next.  With the in-place staging encoder the
        #: async path measures faster on BOTH backends (it hides what
        #: little host staging remains behind device execution; before
        #: the encoder fast path, staging contended with compute on the
        #: CPU backend and async lost 2-6x there) — bench.py's
        #: live_async_round_mean_us tracks this.
        self.use_async_windows = True
        #: CommitControl template cache: all fields but ``end0`` are
        #: constant within (leader, term, cid, live) — rebuilding seven
        #: device scalars per round is measurable host overhead.
        self._ctrl_cache: Optional[tuple] = None
        self._jax = jax
        self._warmup()
        # Recompile sentinel baseline: _warmup just exercised every
        # live dispatch signature, so further backend compiles on this
        # plane are a bug class (the PR 3 mid-leadership stall) —
        # alarm, not archaeology.  Our own build's compiles go into
        # the expected ledger first.
        _EXPECTED["count"] += _COMPILES["count"] - _compiles_at_build_start
        self._snapshot_exec_caches()
        self._compile_baseline = unexpected_compiles()
        self._built = True

    def _warmup(self) -> None:
        """Pay the XLA compile up front on a throwaway log: a first
        round that compiles for seconds mid-leadership would hand the
        whole window to the host path (and once wedged a killed
        daemon's zombie driver inside it, pre-fencing)."""
        from apus_tpu.core.cid import Cid
        from apus_tpu.ops.logplane import make_device_log

        B, SB, R = self.batch, self.slot_bytes, self.n_replicas
        devlog = make_device_log(R, self.n_slots, SB, batch=B,
                                 first_idx=1, leader=0, term=1,
                                 sharding=self._sharding)
        bdata, bmeta = self._place(np.zeros((B, SB), np.uint8),
                                   np.zeros((B, 4), np.int32), 0)
        self._jax.block_until_ready(bdata)
        ctrl = self._make_ctrl(Cid.initial(min(R, 13)), 0, 1, 1,
                               live=set(range(R)))
        devlog, acks, commit = self._step(devlog, bdata, bmeta, ctrl)
        self._jax.block_until_ready(self._pack_result(acks, commit))
        # CHAINED second dispatch: feeding the device-resident outputs
        # back re-specializes the program once (the jit cache keys on
        # the operands' output shardings, which differ from
        # make_device_log's fresh placement).  Without this the SECOND
        # live round pays that compile mid-leadership — ~0.5 s on a
        # loaded CPU host, which races the driver's stall watchdog and
        # flips commit ownership to the host path for no real fault.
        devlog, acks, commit = self._step(devlog, bdata, bmeta, ctrl)
        self._jax.block_until_ready(self._pack_result(acks, commit))
        # Pipelined program too (compiled now, never mid-leadership),
        # reusing the step's returned devlog — a second make_device_log
        # would allocate+transfer another full shard set just to warm a
        # compile that only needs shapes/shardings.  (Rounds land in
        # scratch: the warm devlog's end is past ctrl.end0 — harmless.)
        for depth, pipe in self._pipes.items():
            sdata, smeta = self._place_staged(
                np.zeros((depth, B, SB), np.uint8),
                np.zeros((depth, B, 4), np.int32), 0)
            devlog, commits, _ = pipe(devlog, sdata, smeta, ctrl)
            self._jax.block_until_ready(commits)
        # Windowed (single-window latency) engine: round count and the
        # halt policy are runtime scalars, so ONE warm dispatch compiles
        # the program every shallow depth shares.  ctrl is donated —
        # rebuild a throwaway one for the warm call.
        sdata, smeta = self._place_staged(
            np.zeros((self.PIPE_DEPTH, B, SB), np.uint8),
            np.zeros((self.PIPE_DEPTH, B, 4), np.int32), 0)
        # Two dispatches, replaying commit_window's LIVE ctrl-cache
        # sequence: the first runs with a fresh host-valued ctrl, then
        # the donated output masks (ctrl2 — device-resident,
        # differently-sharded arrays) are adopted into _ctrl_cache
        # exactly as commit_window does, and the second dispatch runs
        # with the cache-derived ctrl.  That second SIGNATURE is what
        # every live window after the first uses — unwarmed, it cost a
        # ~0.5 s recompile on the SECOND client op of each fresh
        # leadership, tripping the stall watchdog into a host-path
        # fallback with no real fault.
        self._ctrl_cache = None
        wcid = Cid.initial(min(R, 13))
        wctrl = self._make_ctrl(wcid, 0, 1, 1, live=set(range(R)))
        devlog, commits, rounds_run, wctrl2 = self._window(
            devlog, sdata, smeta, wctrl, self.PIPE_DEPTH, 1)
        self._jax.block_until_ready(self._pack_result(commits, rounds_run))
        self._ctrl_cache = (self._ctrl_cache[0], wctrl2)
        wctrl = self._make_ctrl(wcid, 0, 1, 1, live=set(range(R)))
        devlog, commits, rounds_run, wctrl2 = self._window(
            devlog, sdata, smeta, wctrl, self.PIPE_DEPTH, 1)
        self._jax.block_until_ready(self._pack_result(commits, rounds_run))
        # Adopt the latest donated masks (the previous generation was
        # just consumed by donation — live commit_window re-adopts the
        # same way after every dispatch).
        self._ctrl_cache = (self._ctrl_cache[0], wctrl2)
        # Single-round step with the cache-derived (device-resident)
        # ctrl too: a live commit_round that follows any window round
        # sees this signature via the shared _make_ctrl cache.
        devlog, acks, commit = self._step(
            devlog, bdata, bmeta,
            self._make_ctrl(wcid, 0, 1, 1, live=set(range(R))))
        self._jax.block_until_ready(self._pack_result(acks, commit))
        # Deep pipes with the cache-derived ctrl too (pipes never
        # donate ctrl, so the cached masks survive): a live deep
        # dispatch that follows ANY window dispatch derives its ctrl
        # from the donated masks — unwarmed, the FIRST deep window of
        # such a leadership paid a mid-leadership XLA recompile.
        # Found by this PR's recompile sentinel on its first run; the
        # exact sibling of the PR 3 second-window stall.
        for depth, pipe in self._pipes.items():
            pdata2, pmeta2 = self._place_staged(
                np.zeros((depth, B, SB), np.uint8),
                np.zeros((depth, B, 4), np.int32), 0)
            devlog, commits, _ = pipe(
                devlog, pdata2, pmeta2,
                self._make_ctrl(wcid, 0, 1, 1, live=set(range(R))))
            self._jax.block_until_ready(commits)
        self._ctrl_cache = None          # warm ctrl is throwaway
        # Reader paths too (follower drain batch + window gathers,
        # shard_end poll): their first use otherwise compiles
        # mid-drain, stalling a live follower for seconds.
        for n in (B, B * self.DEEP_DEPTH):
            self._jax.block_until_ready(self._gather(
                devlog.data, devlog.meta, np.int32(0),
                np.zeros(n, np.int32)))
        self._jax.block_until_ready(self._offs_one(devlog.offs,
                                                   np.int32(0)))

    # -- device-plane telemetry (recompile sentinel + dispatch timing) ----

    def _executables(self) -> list:
        """(name, jitted fn) for every live executable whose compile
        cache the sentinel watches.  Anything without a ``_cache_size``
        probe (plain-python fallbacks) is skipped."""
        out = []
        for attr in ("_step", "_window", "_gather", "_offs_one",
                     "_pack_result", "_place_dev", "_place_staged_dev"):
            fn = getattr(self, attr, None)
            if fn is not None and hasattr(fn, "_cache_size"):
                out.append((attr.lstrip("_"), fn))
        for depth, pipe in getattr(self, "_pipes", {}).items():
            if hasattr(pipe, "_cache_size"):
                out.append((f"pipe{depth}", pipe))
        return out

    def _snapshot_exec_caches(self) -> None:
        self._exec_cache_sizes = {name: fn._cache_size()
                                  for name, fn in self._executables()}

    def check_recompiles(self) -> list:
        """Recompile sentinel.  The alarm signal is jax's own
        backend-compile event stream: any compile past what builds/
        warmups accounted for is a post-warmup XLA compile racing live
        traffic — the PR 3 mid-leadership ~0.5 s stall class, which
        tripped the stall watchdog and flipped commit ownership with
        no real fault.  (The C++ fastpath jit caches can grow per call
        signature WITHOUT compiling, so cache sizes alone over-report;
        they are used only to ATTRIBUTE a detected compile to an
        executable.)  Each detection is reported once (the watermark
        advances) and counted in ``dev_recompiles``; the driver turns
        every report into a flight-recorder event.  Returns
        ``[(executable_name, old_cache, new_cache), ...]`` — name
        "unknown" when no watched cache grew (the compile came from
        outside the watched set)."""
        if self._exec_cache_sizes is None:
            return []
        # Attribution sweep (always, so the hints stay current).
        grown = []
        for name, fn in self._executables():
            cur = fn._cache_size()
            old = self._exec_cache_sizes.get(name, 0)
            if cur > old:
                grown.append((name, old, cur))
                self._exec_cache_sizes[name] = cur
        unexpected = unexpected_compiles()
        delta = unexpected - self._compile_baseline
        if delta <= 0:
            return []
        self._compile_baseline = unexpected
        self.stats.bump("recompiles", delta)
        return grown if grown else [("unknown", 0, 0)]

    def _observe_dispatch_wait(self, seconds: float) -> None:
        """Fold one blocked device->host result wait into the
        telemetry: the per-dispatch wait histogram (µs) plus the
        max-wait gauge the stall watchdog scales to."""
        ms = seconds * 1e3
        if ms > self._max_dispatch.value:
            self._max_dispatch.set(ms)
        self._dispatch_wait_hist.observe(int(seconds * 1e6))

    #: bytes of wire-codec overhead per slot payload (encode_entry
    #: header + optional cid, upper bound).  The authoritative gate is
    #: ``wire.entry_wire_size(e) <= slot_bytes`` (commit_round and the
    #: driver's oversize check); max_data_bytes is the conservative
    #: sizing contract the segmentation layer cuts records against.
    WIRE_OVERHEAD = 64

    def max_data_bytes(self) -> int:
        return self.slot_bytes - self.WIRE_OVERHEAD

    def covers_replica(self, slot: int) -> bool:
        """Whether ``slot``'s shard exists in the device geometry (the
        in-process runner's geometry is the static 0..n_replicas-1; a
        joiner beyond it has no shard)."""
        return 0 <= slot < self.n_replicas

    def quorum_coverable(self, cid) -> bool:
        """Whether the device geometry can own commit for ``cid``
        (every configured member must have a shard here — the
        in-process runner has no clique notion; the mesh runner
        overrides with clique-quorum coverage)."""
        return cid.extended_group_size <= self.n_replicas

    # -- lifecycle of a leadership ---------------------------------------

    def reset(self, leader: int, term: int, first_idx: int) -> Optional[int]:
        """Fresh device log for a new leadership: all shards empty at
        ``first_idx``, fence granted to ``leader``@``term``.  Returns the
        new generation token; rounds from older generations are
        discarded.  Stale terms are REFUSED (None): a zombie driver of a
        killed daemon (its node frozen as leader of an old term) must
        not hijack the runner out from under the live leadership — the
        device-plane form of term fencing (cf. QP-reset fencing,
        dare_ibv_rc.c:2156-2255)."""
        self._build()
        from apus_tpu.ops.logplane import make_device_log
        with self.lock:
            if term < self._term:
                return None
            self.generation += 1
            self._devlog = make_device_log(
                self.n_replicas, self.n_slots, self.slot_bytes,
                batch=self.batch, first_idx=first_idx, leader=leader,
                term=term, sharding=self._sharding)
            self._next_end0 = first_idx
            self._leader, self._term = leader, term
            self.stats.bump("resets")
            if self.logger is not None:
                self.logger.info(
                    "device plane reset: gen=%d leader=%d term=%d base=%d",
                    self.generation, leader, term, first_idx)
            return self.generation

    # -- leader round -----------------------------------------------------

    def commit_round(self, gen: int, end0: int, entries: list[LogEntry],
                     cid, live: set[int]) -> Optional[tuple[list, int]]:
        """Run one commit round: scatter ``entries`` (exactly one batch,
        idx-contiguous from ``end0``) to every shard and evaluate the
        masked quorum.  Returns (acks, device_commit) or None if ``gen``
        is stale."""
        B, SB = self.batch, self.slot_bytes
        assert len(entries) == B, (len(entries), B)
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            assert end0 == self._next_end0, (end0, self._next_end0)
            leader, term = self._leader, self._term
        # Host-side encode + staging run with the runner lock RELEASED.
        # Lock discipline (donation-safe): every *enqueue* touching
        # self._devlog happens under the lock (enqueues are fast —
        # compile was paid in _warmup), because the step DONATES the
        # devlog buffers and a reader enqueueing on a donated array
        # would crash; every *blocking wait* happens outside it, so
        # follower drains and shard_end polls never serialize behind a
        # round's device execution (nor behind a hung dispatch).
        bdata, bmeta = self._encode_batch(entries, end0)
        pdata, pmeta = self._place(bdata, bmeta, leader)
        ctrl = self._make_ctrl(cid, leader, term, end0, live)
        del bdata, bmeta
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None            # reset raced the staging: discard
            assert end0 == self._next_end0, (end0, self._next_end0)
            new_devlog, acks, commit = self._step(self._devlog, pdata,
                                                  pmeta, ctrl)
            self._devlog = new_devlog
            self._next_end0 = end0 + B
            self.stats.bump("rounds")
            self.stats.bump("entries_devplane", B)
            self.depth_histogram[1] = self.depth_histogram.get(1, 0) + 1
            self._window_depth_hist.observe(1)
        t0 = time.monotonic()
        if self._use_device_expand:
            # One blocked device->host transfer per round (two separate
            # readbacks pay two relay round trips on a tunneled chip).
            packed = np.asarray(self._pack_result(acks, commit))
            acks_host = [int(a) for a in packed[:-1]]
            commit_host = int(packed[-1])
        else:
            # CPU backend: no relay to save; the extra pack dispatch
            # costs more than the second host conversion (same rationale
            # as _use_device_expand).
            acks_host = [int(a) for a in np.asarray(acks)]
            commit_host = int(np.asarray(commit))
        self._observe_dispatch_wait(time.monotonic() - t0)
        if commit_host < end0 + B:
            self.stats.bump("quorum_fail_rounds")
        return acks_host, commit_host

    def _encode_batch(self, entries: list[LogEntry], end0: int,
                      out_data=None, out_meta=None):
        """Wire-encode one idx-contiguous batch into slot rows —
        directly into ``out_data``/``out_meta`` when provided (window
        staging encodes thousands of entries; in-place encoding is
        ~4x the speed of per-entry bytes construction)."""
        B, SB = self.batch, self.slot_bytes
        bdata = np.zeros((B, SB), np.uint8) if out_data is None else out_data
        bmeta = np.zeros((B, 4), np.int32) if out_meta is None else out_meta
        flat = memoryview(bdata.reshape(-1))
        for j, e in enumerate(entries):
            assert e.idx == end0 + j, (e.idx, end0, j)
            size = wire.entry_wire_size(e)
            if size > SB:
                raise ValueError(
                    f"entry {e.idx} wire size {size} > slot "
                    f"{SB}; segment upstream")
            wire.encode_entry_into(e, flat, j * SB)
            bmeta[j] = (e.req_id & 0x7FFFFFFF, e.clt_id & 0x7FFFFFFF,
                        int(e.type), size)
        return bdata, bmeta

    def commit_rounds(self, gen: int, end0: int, entries: list[LogEntry],
                      cid, live: set[int]) -> Optional[int]:
        """A multi-round window in ONE dispatch — PIPE_DEPTH or
        DEEP_DEPTH rounds, keyed by ``len(entries)`` (the live analog
        of the reference's outstanding-WR pipelining; which program
        backs the deep rung is a backend decision made in _build).  ``entries`` is
        depth*batch entries, idx-contiguous from ``end0``.  Returns the
        device commit index after the last round, or None if ``gen`` is
        stale.  Same lock discipline as commit_round."""
        h = self.commit_rounds_async(gen, end0, entries, cid, live)
        return None if h is None else self.resolve_rounds(h)

    def commit_window(self, gen: int, end0: int, entries: list[LogEntry],
                      cid, live: set[int]) -> Optional[tuple[int, int]]:
        """The single-window latency path: 1..PIPE_DEPTH rounds in ONE
        dispatch of the windowed engine with ``halt_on_fail=1`` — the
        device exits the moment the outcome is decided (all staged
        votes cleared, or a vote failed and the host must intervene).
        Returns ``(device_commit, rounds_run)`` or None if ``gen`` is
        stale.  On a quorum failure ``rounds_run < n`` and the runner's
        cursor is rewound to the device's true end (entries past the
        failed round were never written anywhere); the caller must
        mirror its own cursor from ``rounds_run``.

        Sync by contract (it reads ``rounds_run`` back); the deep/async
        paths stay on commit_rounds/commit_rounds_async.  Same lock
        discipline as commit_round: enqueues under the runner lock,
        blocking waits outside it."""
        B, W = self.batch, self.PIPE_DEPTH
        n = len(entries) // B
        assert 1 <= n <= W and len(entries) == n * B, (len(entries), n, B)
        t_wall = time.monotonic()
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            assert end0 == self._next_end0, (end0, self._next_end0)
            leader, term = self._leader, self._term
        slot = self._staging.acquire(W)
        bd, bm = slot.data, slot.meta
        for k in range(n):
            self._encode_batch(entries[k * B:(k + 1) * B], end0 + k * B,
                               out_data=bd[k], out_meta=bm[k])
        sdata, smeta = self._place_staged(bd, bm, leader)
        self._staging.staged(slot, (sdata, smeta))
        ctrl = self._make_ctrl(cid, leader, term, end0, live)
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None            # reset raced the staging: discard
            assert end0 == self._next_end0, (end0, self._next_end0)
            new_devlog, commits, rounds_run, ctrl2 = self._window(
                self._devlog, sdata, smeta, ctrl, n, 1)
            self._devlog = new_devlog
            if self._ctrl_cache is not None:   # donated masks (see async)
                self._ctrl_cache = (self._ctrl_cache[0], ctrl2)
            # Optimistic cursor: early exit only diverges on quorum
            # failure; corrected below once rounds_run is known (this
            # runner has a single dispatcher, so no window can slip in
            # between at the stale cursor).
            self._next_end0 = end0 + n * B
            self.stats.bump("window_dispatches")
            self.depth_histogram[n] = self.depth_histogram.get(n, 0) + 1
            self._window_depth_hist.observe(n)
        t0 = time.monotonic()
        packed = np.asarray(self._pack_result(commits, rounds_run))
        self._observe_dispatch_wait(time.monotonic() - t0)
        commits_host, rr = packed[:-1], int(packed[-1])
        commit_host = int(commits_host[max(rr - 1, 0)])
        self._window_wall_hist.observe(
            int((time.monotonic() - t_wall) * 1e6))
        with self.lock:
            if gen != self.generation:
                return None
            self.stats.bump("rounds", rr)
            self.stats.bump("entries_devplane", rr * B)
            self._rounds_run_hist.observe(rr)
            if rr < n:
                # Requested depth vs early-exit round: the occupancy
                # evidence that a quorum failure cut the window short.
                self.stats.bump("early_exits")
            qf = int(sum(int(commits_host[k]) < end0 + (k + 1) * B
                         for k in range(rr)))
            if qf:
                self.stats.bump("quorum_fail_rounds", qf)
            if rr < n and self._next_end0 == end0 + n * B:
                # Quorum failed at round rr-1: rounds rr..n-1 never
                # executed anywhere — rewind the contiguity cursor to
                # the device's true end.
                self._next_end0 = end0 + rr * B
        return commit_host, rr

    def commit_rounds_async(self, gen: int, end0: int,
                            entries: list[LogEntry], cid,
                            live: set[int]) -> Optional["_WindowHandle"]:
        """Enqueue a multi-round window WITHOUT waiting for its result —
        the caller may stage and dispatch the next window while this
        one executes, then collect via :meth:`resolve_rounds`.  This is
        the sharper analog of the reference's outstanding-WR
        pipelining: post_send keeps the NIC queue full and only
        selectively signals (dare_ibv_rc.c:2552-2568); here the device
        queue holds whole windows and the host blocks only at resolve.
        Returns None if ``gen`` is stale.  Donation keeps device-side
        ordering: window N+1's program consumes the devlog arrays
        window N produced, whether or not N has been resolved."""
        B = self.batch
        K = len(entries) // B
        # Deep rungs ride their per-depth pipelined programs; shallow
        # depths (<= PIPE_DEPTH) ride the single-window engine with a
        # runtime round count (halt_on_fail=0 preserves the pipelined
        # contract: all K rounds always run).
        use_window = K not in self._pipes
        assert len(entries) == K * B and \
            (not use_window or 1 <= K <= self.PIPE_DEPTH), \
            (len(entries), K, B, sorted(self._pipes))
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            assert end0 == self._next_end0, (end0, self._next_end0)
            leader, term = self._leader, self._term
        # Host-side window encoding into a REUSABLE double-buffered
        # staging pair (ops.logplane.HostStagingRing): packing window
        # N+1 overlaps the device executing window N; acquire blocks
        # only on the consumer edge of this pair's previous transfer.
        slot = self._staging.acquire(self.PIPE_DEPTH if use_window else K)
        bd, bm = slot.data, slot.meta
        for k in range(K):
            self._encode_batch(entries[k * B:(k + 1) * B], end0 + k * B,
                               out_data=bd[k], out_meta=bm[k])
        sdata, smeta = self._place_staged(bd, bm, leader)
        self._staging.staged(slot, (sdata, smeta))
        ctrl = self._make_ctrl(cid, leader, term, end0, live)
        del bd, bm
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None            # reset raced the staging: discard
            assert end0 == self._next_end0, (end0, self._next_end0)
            if use_window:
                new_devlog, commits, _rr, ctrl2 = self._window(
                    self._devlog, sdata, smeta, ctrl, K, 0)
                # The engine DONATES ctrl (vote-mask buffers alias
                # input->output): the cached ctrl's masks now live in
                # ctrl2 — refresh the cache so the next _make_ctrl hit
                # replaces end0 on live buffers, not donated ones.
                if self._ctrl_cache is not None:
                    self._ctrl_cache = (self._ctrl_cache[0], ctrl2)
            else:
                new_devlog, commits, _ = self._pipes[K](
                    self._devlog, sdata, smeta, ctrl)
            self._devlog = new_devlog
            self._next_end0 = end0 + K * B
            self.stats.bump("rounds", K)
            self.stats.bump("entries_devplane", K * B)
            self.stats.bump("pipelined_dispatches")
            self.depth_histogram[K] = self.depth_histogram.get(K, 0) + 1
            self._window_depth_hist.observe(K)
            if K >= self.DEEP_DEPTH:
                self.stats.bump("deep_dispatches")
        return _WindowHandle(gen, end0, K, commits)

    def resolve_rounds(self, h: "_WindowHandle") -> Optional[int]:
        """Block on an async window's result and return the device
        commit index after its last round.  Returns None if the runner
        has been reset since the window was enqueued — its device
        result was computed against a generation whose quorum attests
        the caller must no longer act on."""
        t0 = time.monotonic()
        commits_host = np.asarray(h.commits)        # device->host wait
        self._observe_dispatch_wait(time.monotonic() - t0)
        B = self.batch
        with self.lock:
            if h.gen != self.generation:
                return None
            # Per-round accounting (parity with the single-round path:
            # a dispatch where all K rounds miss quorum counts K, not 1).
            qf = int(sum(int(commits_host[k]) < h.end0 + (k + 1) * B
                         for k in range(h.K)))
            if qf:
                self.stats.bump("quorum_fail_rounds", qf)
            self._rounds_run_hist.observe(h.K)
        # Index by round count, not -1: the shallow windowed engine
        # returns a max_depth-padded commits vector.
        return int(commits_host[h.K - 1])

    def _make_ctrl(self, cid, leader: int, term: int, end0: int,
                   live: set[int]):
        """CommitControl with the quorum vote masked to live members.
        Masking shrinks only the numerator: quorum thresholds stay
        derived from the full configuration sizes.

        Everything but ``end0`` is constant within a (leader, term, cid,
        live) epoch, so the device scalars are cached and only ``end0``
        is re-staged per round."""
        import dataclasses as _dc

        import jax.numpy as jnp

        from apus_tpu.core.cid import CidState
        from apus_tpu.ops.commit import CommitControl

        key = (leader, term, repr(cid), tuple(sorted(live)))
        if self._ctrl_cache is not None and self._ctrl_cache[0] == key:
            return _dc.replace(self._ctrl_cache[1],
                               end0=jnp.asarray(end0, jnp.int32))
        R = self.n_replicas
        mask_old = np.array(
            [1 if (cid.contains(i) and i < cid.size and i in live) else 0
             for i in range(R)], np.int32)
        if cid.state == CidState.TRANSIT:
            mask_new = np.array(
                [1 if (cid.contains(i) and i < cid.new_size and i in live)
                 else 0 for i in range(R)], np.int32)
            q_new = quorum_size(cid.new_size)
        else:
            mask_new = np.zeros(R, np.int32)
            q_new = 0
        i32 = lambda v: jnp.asarray(v, jnp.int32)   # noqa: E731
        ctrl = CommitControl(i32(leader), i32(term), i32(end0),
                             jnp.asarray(mask_old), jnp.asarray(mask_new),
                             i32(quorum_size(cid.size)), i32(q_new))
        self._ctrl_cache = (key, ctrl)
        return ctrl

    # -- follower shard readback -----------------------------------------

    def shard_end(self, replica: int, gen: int) -> Optional[int]:
        """The device-log end of ``replica``'s shard (None if stale gen
        or ``replica`` outside the device geometry — a joiner beyond
        n_replicas must not silently read another replica's shard via
        JAX index clamping)."""
        from apus_tpu.ops.logplane import OFF_END
        if not (0 <= replica < self.n_replicas):
            return None
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            # Enqueue under the lock (donation safety); the wait for the
            # tiny [4]-int transfer happens outside it.
            row = self._offs_one(self._devlog.offs, np.int32(replica))
        return int(np.asarray(row)[OFF_END])

    def read_rows(self, replica: int, gen: int, lo: int, hi: int,
                  window: bool = False) -> Optional[list[LogEntry]]:
        """Decode rows [lo, hi) from ``replica``'s shard — at most one
        batch, or one DEEP window with ``window=True`` (the follower
        drain's bulk shape: one gather dispatch instead of DEEP_DEPTH,
        which on a tunneled chip is one round trip instead of 16; the
        rc_recover_log analog bulk-reads the same way,
        dare_ibv_rc.c:726-856).  Rows whose stored absolute index no
        longer matches (ring overwritten, or not yet written) are cut
        off; the caller appends what it gets and retries later."""
        from apus_tpu.ops.logplane import META_IDX, META_LEN, slot_of
        if not (0 <= replica < self.n_replicas):
            return None
        cap = self.batch * (self.DEEP_DEPTH if window else 1)
        hi = min(hi, lo + cap)
        # Two static slot-vector shapes ([B] and [DEEP*B]) -> two
        # compiled gathers (jit retraces per shape); rows past hi are
        # fetched and discarded.
        n = self.batch if hi - lo <= self.batch else cap
        slots = slot_of(lo + np.arange(n, dtype=np.int64),
                        self.n_slots).astype(np.int32)
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            if hi <= lo:
                return []
            # Enqueue under the lock (donation safety: the commit step
            # donates the devlog buffers, so reader enqueues must be
            # ordered against round dispatches); the device->host wait
            # happens outside it.
            data_rows, meta_rows = self._gather(
                self._devlog.data, self._devlog.meta,
                np.int32(replica), slots)
        data = np.asarray(data_rows)
        meta = np.asarray(meta_rows)
        out: list[LogEntry] = []
        for j, idx in enumerate(range(lo, hi)):
            if int(meta[j, META_IDX]) != idx:
                break
            n = int(meta[j, META_LEN])
            blob = data[j, :n].tobytes()
            try:
                e = wire.decode_entry(wire.Reader(blob))
            except Exception:
                break
            if e.idx != idx:
                break
            out.append(e)
        return out


class _WindowHandle:
    """In-flight async window (commit_rounds_async): the device-side
    ``commits`` vector plus the expectations needed to account for it
    at resolve time."""

    __slots__ = ("gen", "end0", "K", "commits")

    def __init__(self, gen: int, end0: int, K: int, commits):
        self.gen, self.end0, self.K, self.commits = gen, end0, K, commits


class DevicePlaneDriver:
    """Per-daemon thread binding one replica to the shared runner."""

    #: Deep windows kept in flight before the driver blocks on the
    #: oldest one — the reference keeps its NIC send queue full the
    #: same way (sized 2*ceil(retry/hb), selective signaling,
    #: dare_ibv_rc.c:182-195, :2552-2568).  Two in flight overlaps
    #: window N+1's staging+dispatch with window N's execution; the
    #: third absorbs submission jitter on a relay-tunneled chip (where
    #: dispatch RTT >> execution, an empty device queue between
    #: resolves is pure dead time).  Deeper than that only adds
    #: commit-release latency.
    MAX_INFLIGHT = 3

    def __init__(self, daemon, runner: DeviceCommitRunner):
        self.daemon = daemon
        self.runner = runner
        self.logger = daemon.logger
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Leader-side round state (valid while _gen matches the runner).
        self._gen: Optional[int] = None
        self._dev_base = 0
        self._dev_next = 0
        self._last_end_seen = 0
        self._last_commit_advance = 0.0
        # In-flight async deep windows, oldest first (commit_rounds_
        # async handles); dropped whenever _gen is invalidated.
        self._inflight: list[_WindowHandle] = []
        # Follower-side: skip drain polling while nothing new happened
        # (keyed on (generation, rounds) at the last fruitless drain).
        self._drain_idle_key = None
        # After a stall fallback, device work pauses and commit
        # ownership may not be re-armed until this deadline passes AND
        # the cursor has caught up (prevents a 0.5 s own/stall flap).
        self._cooldown_until = 0.0
        # Quorum-fail timeout (partial-partition hardening): when
        # dispatched windows keep missing quorum — the live mask was
        # stale, or peers ack on TCP but their shard acks stopped —
        # the streak is bounded by the watchdog window; past it the
        # host path takes commit back and dispatch PAUSES instead of
        # hot-looping guaranteed-failing windows (each one burns a
        # device dispatch and rewinds the cursor it just advanced).
        self._qfail_since: Optional[float] = None
        self._qfail_pause_until = 0.0
        self._gate_since: Optional[float] = None
        self.stats = {"rounds": 0, "drained": 0, "holes": 0,
                      "fallbacks": 0, "partial_deferrals": 0}

    def _set_owned(self, node, owned: bool, cause: str) -> None:
        """Flip device-plane commit ownership (under the daemon lock),
        leaving a cause-tagged flight event + counter behind — every
        ``owns_commit`` transition becomes attributable from a
        black-box dump (stall watchdog vs quorum-fail streak vs
        leadership warmup vs cursor catch-up), instead of a mystery
        boolean observed after the fact."""
        if bool(node.external_commit) == owned:
            return
        node.external_commit = owned
        node.bump("devplane_own_flips")
        node._note("devplane", "own" if owned else "release",
                   cause=cause, commit=node.log.commit,
                   dev_next=self._dev_next)

    def _check_recompiles(self, node) -> None:
        """Drain the runner's recompile sentinel into the flight
        recorder (called under the daemon lock after dispatch
        adoption; the sentinel itself is a handful of jit-cache size
        probes)."""
        check = getattr(self.runner, "check_recompiles", None)
        if check is None:
            return
        for name, old, new in check():
            node._note("devplane", "recompile", exe=name,
                       cached_before=old, cached_after=new)
            self.logger.warning(
                "device plane: post-warmup XLA recompile on live "
                "executable %r (jit cache %d -> %d)", name, old, new)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        with self.daemon.lock:
            # Election safety: the host log must absorb the device
            # shard before this replica votes or campaigns.
            self.daemon.node.pre_election_hook = self._drain_for_election
            # Stall watchdog runs in the TICK thread: the driver thread
            # itself may be the thing that is wedged (hung dispatch).
            self.daemon.on_tick.append(self._tick_watchdog)
        t = threading.Thread(target=self._run,
                             name=f"apus-devplane-{self.daemon.idx}",
                             daemon=True)
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        with self.daemon.lock:
            node = self.daemon.node
            self._set_owned(node, False, "driver_stop")
            if node.pre_election_hook == self._drain_for_election:
                node.pre_election_hook = None
            if self._tick_watchdog in self.daemon.on_tick:
                self.daemon.on_tick.remove(self._tick_watchdog)

    def _tick_watchdog(self) -> None:
        """Runs under the daemon lock in the tick thread.  If the device
        plane owns commit but hasn't advanced it despite pending
        entries, hand commit back to the host ack path — even (above
        all) when the driver thread is stuck inside a hung device
        dispatch and cannot police itself."""
        node = self.daemon.node
        if not (node.is_leader and node.external_commit):
            return
        window = max(4 * self.daemon.spec.hb_timeout, 0.5)
        # Scale to OBSERVED dispatch latency: on an oversubscribed host
        # a healthy dispatch can exceed the static floor, and flipping
        # ownership on every slow-but-completing window just flaps
        # commit between the paths.  A genuinely wedged dispatch never
        # updates max_dispatch_ms, so the real stall case still trips
        # at the static window.
        md_ms = self.runner.stats.get("max_dispatch_ms")
        if md_ms:
            window = max(window, 2.5 * md_ms / 1e3)
        if node.log.end > node.log.commit and \
                time.monotonic() - self._last_commit_advance > window:
            self._set_owned(node, False, "stall_watchdog")
            self._cooldown_until = time.monotonic() + window
            self.stats["fallbacks"] += 1
            node._note("watchdog", "devplane_stall_fallback",
                       window_s=round(window, 3))
            self.logger.warning("device plane stalled; host commit path "
                                "re-enabled")

    # -- main loop --------------------------------------------------------

    def _run(self) -> None:
        poll = max(self.daemon._tick_interval, 0.0005)
        while not self._stop.is_set():
            try:
                if not self._step_once():
                    time.sleep(poll)
            except Exception:
                self.logger.exception("device-plane driver error")
                self._deactivate()
                time.sleep(10 * poll)

    def _deactivate(self) -> None:
        with self.daemon.lock:
            self._set_owned(self.daemon.node, False, "driver_error")
            self.daemon.node.device_covered_from = None
        self._gen = None
        self._inflight.clear()

    def _step_once(self) -> bool:
        """One driver iteration.  Returns True if work was done (skip
        the idle sleep)."""
        node = self.daemon.node
        # Multi-controller runners (runtime.mesh_plane) build in the
        # background and can die (degrade to TCP) at any point.  A dead
        # plane dispatches nothing, but the FOLLOWER DRAIN continues:
        # completed windows' rows in our local shard must still reach
        # the host log (mesh_plane._die).
        if getattr(self.runner, "dead", False):
            if self._gen is not None or node.external_commit:
                self._deactivate()
            return self._follower_step(node)
        if not getattr(self.runner, "ready", True):
            return False
        with self.daemon.lock:
            if node.is_leader:
                return self._leader_step(node)
            if self._gen is not None:
                self._gen = None
                self._inflight.clear()
                self._set_owned(node, False, "role_change")
        return self._follower_step(node)

    # -- leader half ------------------------------------------------------

    def _leader_step(self, node) -> bool:
        """Called under the daemon lock.  Heavy work (device dispatch)
        runs with the lock RELEASED; results are re-validated after."""
        term = node.current_term
        B = self.runner.batch
        if not self.runner.quorum_coverable(node.cid):
            # The device geometry/clique cannot own quorum for this
            # configuration (outgrown it, or too few clique members):
            # host path owns commit until it can again.
            if self._gen is not None:
                self._gen = None
                self._inflight.clear()
                self._set_owned(node, False, "coverage_lost")
                node.device_covered_from = None
                self.stats["fallbacks"] += 1
            return False

        if self._gen is None or self.runner._term != term \
                or self.runner._leader != node.idx:
            return self._reset_for_leadership(node, term)

        # Re-base when pruning moved past the device cursor: that span
        # can no longer be read out of the host log, so the contiguity
        # chain must restart from a fresh base.  (A host-committed-but-
        # unpruned span is NOT a reason to re-base — the device rounds
        # re-attest it idempotently and catch up to the live edge.)
        if self._dev_next < node.log.head:
            self._gen = None
            self._inflight.clear()
            return True

        # Async pipeline policy: block on the oldest in-flight deep
        # window once the pipeline is full, or as soon as the backlog
        # can no longer fill another deep window (drain when traffic
        # lightens so committed entries release their app threads).
        if self._inflight:
            deep_ready = (node.log.end - self._dev_next
                          >= self.runner.DEEP_DEPTH * B)
            if len(self._inflight) >= self.MAX_INFLIGHT or not deep_ready:
                return self._resolve_oldest(node, term)

        # Re-arm device-owned commit once (a) the host quorum has
        # committed the prefix below the device base (safety argument
        # 1), (b) any stall cooldown has passed, and (c) the device
        # cursor has caught up to the commit frontier — re-owning
        # commit while trailing would immediately stall again.
        if not node.external_commit and node.log.commit >= self._dev_base \
                and time.monotonic() >= self._cooldown_until \
                and self._dev_next >= node.log.commit:
            self._set_owned(node, True, "cursor_catchup")
            # Future-stamp by one watchdog window: freshly-armed
            # ownership gets a doubled first stall check — the first
            # window after arming legitimately covers staging + the
            # first dispatch on a loaded host, and tripping there just
            # flaps ownership straight back off.
            self._last_commit_advance = time.monotonic() + \
                max(4 * self.daemon.spec.hb_timeout, 0.5)
            self.logger.info("device plane owns commit from idx %d",
                             self._dev_base)

        # Partial-partition gate: the quorum vote is masked to members
        # whose control-plane writes were recently observed (safety
        # argument 3), so a window dispatched while the live mask
        # cannot cover quorum is a GUARANTEED quorum-fail round.  An
        # injected partial partition (FaultPlane blocking peers) used
        # to hot-loop exactly that: dispatch, fail, rewind, redispatch
        # — device churn with zero progress.  Gate dispatch instead:
        # drain the pipeline, hand commit to the host path, and wait
        # for the failure detector to see the peers again.
        live_now = self._live_members(node)
        if not self._live_covers_quorum(node.cid, live_now):
            if self._inflight:
                return self._resolve_oldest(node, term)
            self.stats["quorum_gated"] = \
                self.stats.get("quorum_gated", 0) + 1
            now = time.monotonic()
            window = max(4 * self.daemon.spec.hb_timeout, 0.5)
            if self._gate_since is None:
                # Brief shortfalls are scheduler noise (a starved
                # follower's REP_ACK a few ms late), not partitions:
                # skip THIS dispatch but keep commit ownership until
                # the shortfall persists a full watchdog window.
                self._gate_since = now
            elif now - self._gate_since > window and \
                    node.external_commit:
                self._set_owned(node, False, "quorum_gate")
                self._cooldown_until = now + window
                self.stats["fallbacks"] += 1
                self.logger.warning(
                    "device plane: live members %s below quorum of %r; "
                    "host commit path re-enabled", sorted(live_now),
                    node.cid)
            return False
        self._gate_since = None
        # Quorum-fail pause (see __init__): bounded stand-down after a
        # sustained streak of quorum-failing windows.
        if time.monotonic() < self._qfail_pause_until:
            return False

        # A fixed-shape runner (runtime.mesh_plane) dispatches ONE window
        # shape only — the dispatch unit is FIXED_WINDOW batches, and
        # padding/micro-batching work at that granularity.
        fixed = getattr(self.runner, "FIXED_WINDOW", None)
        unit = (fixed or 1) * B
        end = node.log.end
        if end <= self._dev_next:
            return False
        # Micro-batching: take a partial unit only once arrivals pause
        # (one poll of delay), so bursts fill rounds instead of padding.
        # Queue-occupancy feed: ops admitted but NOT YET APPENDED
        # (idx is None) will land in the log next tick (group-commit
        # drain), so a partial window is also deferred while such ops
        # are queued — the window depth the dispatch below picks then
        # reflects the real backlog, not the slice of it that happened
        # to be appended when we looked.  Strictly un-appended ops
        # only: _pending also holds appended-but-uncommitted handles,
        # and gating on those would deadlock (their commit needs this
        # very dispatch).  Gated on log headroom too: a full ring must
        # not wedge dispatch waiting for admissions that cannot land.
        if end - self._dev_next < unit and (
                end != self._last_end_seen
                or (not node.log.near_full(3)
                    and any(p.idx is None for p in node._pending))):
            # Window-occupancy feed: a partial window deferred while
            # admitted-but-unappended ops queue (or arrivals are still
            # landing) — counted so the occupancy question "how often
            # did we wait to fill instead of padding?" is scrapeable.
            self.stats["partial_deferrals"] += 1
            self._last_end_seen = end
            return False
        self._last_end_seen = end
        # Pad a PARTIAL tail to the dispatch boundary with NOOPs
        # (partial batches arrive NOOP-padded by contract; the reference
        # appends NOOPs too, dare_log.h:22).  A backlog >= unit needs no
        # padding — the rounds take real entries from dev_next.
        # (dev_next is B-aligned, so unit-relative padding preserves the
        # global (end0-1) % B == 0 invariant.)
        if end - self._dev_next < unit:
            while (node.log.end - self._dev_next) % unit != 0 \
                    and not node.log.near_full(2):
                node.log.append(term, type=EntryType.NOOP)
            if (node.log.end - self._dev_next) % unit != 0:
                return False               # log full: wait for pruning
            end = node.log.end
        # Pipelined dispatch when the backlog covers a window of clean
        # batches: the deepest available window rides one XLA program
        # (runner.commit_rounds) instead of K dispatch+sync cycles —
        # the deepest ladder rung the backlog covers, else PIPE_DEPTH,
        # else a single round.
        span_rounds = 1
        entries = None
        inflight_rounds = sum(h.K for h in self._inflight)
        for K in self.runner.window_depths:
            if end - self._dev_next < K * B:
                continue
            # Ring-capacity gate: everything in flight (plus this
            # window) must fit in the live ring, or followers could
            # never drain the overwritten spans from their shards (the
            # TCP repair path would carry them instead — safe, but the
            # device transport would be hauling bytes nobody can read).
            if (inflight_rounds + K) * B > self.runner.n_slots:
                continue
            span = list(node.log.entries(self._dev_next,
                                         self._dev_next + K * B))
            if len(span) == K * B and not any(
                    wire.entry_wire_size(e) > self.runner.slot_bytes
                    for e in span):
                entries, span_rounds = span, K
                break
            # This window is dirty (short span or an oversized entry
            # inside it) — a SHALLOWER rung may still be clean; fall
            # through and keep the single-batch prefix as the fallback.
            entries = span[:B] if len(span) >= B else []
        if entries is None:
            entries = list(node.log.entries(self._dev_next,
                                            self._dev_next + B))
        if span_rounds < self.runner.DEEP_DEPTH and self._inflight:
            # A dirty deep window downgraded this dispatch to a sync
            # shape (or an oversize fallback): drain the pipeline first
            # — the sync paths and the host-fallback handoff both
            # assume no outstanding windows.
            return self._resolve_oldest(node, term)
        if fixed is not None and span_rounds != fixed:
            # Fixed-shape runner but the only full window is dirty (an
            # oversized entry inside it): there is no shallower shape to
            # dispatch, so the host path owns this span; re-base past it
            # once the host quorum has committed it through.
            self.stats["holes"] += 1
            self._set_owned(node, False, "oversize_hole")
            if node.log.commit >= self._dev_next + unit:
                self._gen = None           # re-base next iteration
            return False
        if span_rounds == 1:
            if len(entries) != B:
                return False
            if any(wire.entry_wire_size(e) > self.runner.slot_bytes
                   for e in entries):
                # Oversized record: this span must commit via the host
                # path; re-base the device plane past it once that
                # happens.
                self.stats["holes"] += 1
                self._set_owned(node, False, "oversize_hole")
                if node.log.commit >= self._dev_next + B:
                    self._gen = None       # re-base next iteration
                return False
        # Shallow spans ride the single-window engine (one compiled
        # program, runtime round count, quorum-fail early exit) on
        # runners that expose it; the fixed-shape mesh runner and the
        # deep rungs keep their paths.
        use_window = (fixed is None
                      and span_rounds <= self.runner.PIPE_DEPTH
                      and hasattr(self.runner, "commit_window"))
        if use_window and span_rounds == 1:
            # Widen to every clean full batch the backlog holds (the
            # ladder above only probed the fixed rungs): 2..W rounds
            # cost the same dispatch as 1.
            n_max = min((end - self._dev_next) // B,
                        self.runner.PIPE_DEPTH)
            for n in range(n_max, 1, -1):
                span = list(node.log.entries(self._dev_next,
                                             self._dev_next + n * B))
                if len(span) == n * B and not any(
                        wire.entry_wire_size(e) > self.runner.slot_bytes
                        for e in span):
                    entries, span_rounds = span, n
                    break
        gen, end0 = self._gen, self._dev_next
        cid = node.cid
        live = live_now

        # -- device dispatch outside the daemon lock --
        obs = getattr(self.daemon, "obs", None)
        if obs is not None:
            # Device-plane span: window [end0, end0+K*B) handed to the
            # jitted engine (idx-range ring event; dev_ready pairs it
            # at commit adoption).
            obs.spans.window_event("dev_dispatch", end0,
                                   end0 + span_rounds * B)
        handle = None
        win = None
        self.daemon.lock.release()
        try:
            if span_rounds >= self.runner.DEEP_DEPTH \
                    and self.runner.use_async_windows:
                # Deep windows enqueue WITHOUT blocking on the result:
                # up to MAX_INFLIGHT ride the device queue while the
                # host stages the next (the outstanding-WR shape).
                handle = self.runner.commit_rounds_async(
                    gen, end0, entries, cid, live)
                res = None if handle is None else ()
            elif use_window:
                win = self.runner.commit_window(gen, end0, entries, cid,
                                                live)
                res = None if win is None else ()
            elif span_rounds > 1:
                dev_commit = self.runner.commit_rounds(gen, end0, entries,
                                                       cid, live)
                res = None if dev_commit is None else ((), dev_commit)
            else:
                res = self.runner.commit_round(gen, end0, entries, cid,
                                               live)
        finally:
            self.daemon.lock.acquire()
        # Sentinel sweep right after the dispatch: a recompile that
        # happened inside it is attributed to THIS window's flight
        # events, not discovered by archaeology a campaign later.
        self._check_recompiles(node)

        if res is None:                    # stale generation
            self._gen = None
            self._inflight.clear()
            return True
        if win is not None:
            # The engine may have early-exited on a quorum failure:
            # mirror the runner's rewound cursor from rounds_run.
            dev_commit, rounds_run = win
            self._dev_next = end0 + rounds_run * B
            self.stats["rounds"] += rounds_run
            if self._stop.is_set() \
                    or not (node.is_leader and node.current_term == term):
                self._gen = None
                self._inflight.clear()
                return True
            self._adopt_commit(node, dev_commit)
            self._note_quorum_result(node, dev_commit > end0)
            return True
        self._dev_next = end0 + span_rounds * B
        self.stats["rounds"] += span_rounds
        if handle is not None:
            self._inflight.append(handle)
            self.stats["async_windows"] = \
                self.stats.get("async_windows", 0) + 1
            return True
        acks, dev_commit = res
        # Re-validate leadership before adopting the result: an election
        # (or our own daemon's death) may have happened while the lock
        # was released.
        if self._stop.is_set() \
                or not (node.is_leader and node.current_term == term):
            self._gen = None
            self._inflight.clear()
            return True
        self._adopt_commit(node, dev_commit)
        self._note_quorum_result(node, dev_commit > end0)
        return True

    def _resolve_oldest(self, node, term: int) -> bool:
        """Block on the oldest in-flight async window (daemon lock
        released during the wait) and adopt its quorum result after the
        same re-validation as the sync paths.  Called under the daemon
        lock; always consumes the handle."""
        h = self._inflight[0]
        self.daemon.lock.release()
        try:
            dev_commit = self.runner.resolve_rounds(h)
        finally:
            self.daemon.lock.acquire()
        self._check_recompiles(node)
        if self._inflight and self._inflight[0] is h:
            self._inflight.pop(0)
        if dev_commit is None:             # runner reset since enqueue
            self._gen = None
            self._inflight.clear()
            return True
        if self._stop.is_set() \
                or not (node.is_leader and node.current_term == term):
            self._gen = None
            self._inflight.clear()
            return True
        self._adopt_commit(node, dev_commit)
        return True

    def _adopt_commit(self, node, dev_commit: int) -> None:
        """Advance host commit from a device quorum result (under the
        daemon lock, leadership already re-validated).  Capped by any
        live follower read lease's missing HOST ack (flr_commit_cap):
        new grants are refused while the device plane owns commit, but
        a grant issued just before the ownership flip keeps binding
        until it expires — the device quorum attests SHARD placement,
        not the holder's host log, and the holder serves reads from
        its host-applied state."""
        cap = node.flr_commit_cap()
        if cap is not None:
            dev_commit = min(dev_commit, cap)
        if node.log.commit >= self._dev_base and dev_commit > node.log.commit:
            before = node.log.commit
            after = node.log.advance_commit(min(dev_commit, node.log.end))
            if after > before:
                self._last_commit_advance = time.monotonic()
                obs = getattr(self.daemon, "obs", None)
                if obs is not None:
                    # Device quorum advanced commit: pair of the
                    # dev_dispatch event, plus the per-op quorum stage
                    # for sampled ops in the window.
                    obs.spans.window_event("dev_ready", before, after)
                    obs.spans.stamp_range("quorum", before, after)
                node.bump("commits")
                node.bump("devplane_commits")
                self.daemon.commit_cond.notify_all()

    def _reset_for_leadership(self, node, term: int) -> bool:
        """New leadership: choose the device base just past our current
        log end (guaranteeing a term-T entry sits below it — the blank
        entry from become_leader at minimum) and reset the shards."""
        B = self.runner.batch
        self._inflight.clear()      # any survivors are stale post-reset
        while (node.log.end - 1) % B != 0 and not node.log.near_full(2):
            node.log.append(term, type=EntryType.NOOP)
        if (node.log.end - 1) % B != 0:
            return False
        base = node.log.end
        idx = node.idx
        self.daemon.lock.release()
        try:
            gen = self.runner.reset(idx, term, base)
        finally:
            self.daemon.lock.acquire()
        if gen is None or self._stop.is_set() \
                or not (node.is_leader and node.current_term == term):
            return True
        self._gen = gen
        self._dev_base = base
        self._dev_next = base
        self._last_end_seen = 0
        # Same doubled first-check grace as the re-arm path.
        self._last_commit_advance = time.monotonic() + \
            max(4 * self.daemon.spec.hb_timeout, 0.5)
        # Host ack quorum owns commit until it has covered the prefix
        # below the device base; under load that may already be true by
        # the time the shards are rebuilt — take over immediately then,
        # or the racing host path keeps outrunning every fresh base.
        self._set_owned(node, node.log.commit >= base,
                        "leadership_reset")
        node.device_covered_from = base
        if node.external_commit:
            self.logger.info("device plane owns commit from idx %d", base)
        return True

    def _live_covers_quorum(self, cid, live: set[int]) -> bool:
        """Whether the live-mask can still clear the device quorum vote
        for ``cid`` (thresholds stay full-configuration sizes — masking
        shrinks only the numerator, safety argument 3)."""
        from apus_tpu.core.cid import CidState
        old = sum(1 for m in live if cid.contains(m) and m < cid.size)
        if old < quorum_size(cid.size):
            return False
        if cid.state == CidState.TRANSIT:
            new = sum(1 for m in live
                      if cid.contains(m) and m < cid.new_size)
            if new < quorum_size(cid.new_size):
                return False
        return True

    def _note_quorum_result(self, node, advanced: bool) -> None:
        """Track the quorum-fail streak across dispatched windows
        (called under the daemon lock with the result of each resolved
        window).  A streak longer than the watchdog window trips the
        quorum-fail timeout: commit back to the host path, dispatch
        paused for one window — the cursor was already rewound by the
        engine, so the span redispatches cleanly after the pause."""
        if advanced:
            self._qfail_since = None
            return
        now = time.monotonic()
        if self._qfail_since is None:
            self._qfail_since = now
            return
        window = max(4 * self.daemon.spec.hb_timeout, 0.5)
        if now - self._qfail_since > window:
            self._qfail_since = None
            self._qfail_pause_until = now + window
            if node.external_commit:
                self._set_owned(node, False, "quorum_fail_streak")
                self.stats["fallbacks"] += 1
            self._cooldown_until = max(self._cooldown_until, now + window)
            self.stats["qfail_timeouts"] = \
                self.stats.get("qfail_timeouts", 0) + 1
            self.logger.warning(
                "device plane: quorum-fail streak past %.2f s; host "
                "commit path re-enabled, dispatch paused", window)

    def _live_members(self, node) -> set[int]:
        """Members whose control-plane writes were recently observed
        (plus ourselves).  Window = the failure-detector timeout, with
        a 0.25 s floor: the reference trusts RDMA acks until retry
        exhaustion (~seconds), and a tighter floor makes in-process
        clusters (one GIL, follower ticks starved for hundreds of ms
        by a sibling's dispatch) flap the mask on scheduler noise."""
        window = max(node._hb_timeout, 4 * self.daemon.spec.hb_period,
                     0.25)
        now = time.monotonic()
        live = {node.idx}
        touched = node.regions.touched
        for m in node.cid.members():
            if m == node.idx:
                continue
            t = touched.get((Region.REP_ACK, m))
            if t is not None and now - t <= window:
                live.add(m)
        return live

    # -- election-time shard reconciliation -------------------------------

    def _drain_for_election(self) -> None:
        """node.pre_election_hook: runs UNDER the daemon lock, from the
        tick thread, before this replica grants a real vote or
        campaigns.  The host log absorbs every current-term row the
        replica's own device shard holds: the device quorum attests
        SHARD placement (safety argument 1/3), so the shard must count
        as the log for election up-to-dateness (node.py pre_election_hook
        contract) — exactly as the reference's recovery reads back the
        same memory its RDMA writes landed in (rc_recover_log,
        dare_ibv_rc.c:726-856).  Same term/idx/prev-entry guards as
        _follower_step; loops until shard_end is absorbed or a guard
        fails (tail not at current term, decode hole, full log)."""
        node = self.daemon.node
        if not self.runner.covers_replica(self.daemon.idx):
            return
        # Multi-controller runner: every window this process dispatched
        # must finish executing BEFORE the vote below, or shard acks
        # could commit entries the election never covered (mesh_plane
        # docstring, election safety).  Unready windows VETO the vote
        # (return False -> node defers a tick) rather than block the
        # daemon here.
        quiesce = getattr(self.runner, "quiesce_ready", None)
        if quiesce is not None and not quiesce():
            return False
        while True:
            gen = self.runner.generation
            if gen == 0:
                return
            term = node.current_term
            end = node.log.end
            prev = node.log.get(end - 1)
            if prev is None or prev.term != term:
                return                 # diverged/stale tail: do not graft
            shard_end = self.runner.shard_end(self.daemon.idx, gen)
            if shard_end is None or shard_end <= end:
                return                 # shard fully absorbed
            # Bulk shape (one gather per deep window, not per batch):
            # this hook runs under the daemon lock pre-vote, so every
            # saved device round trip directly shortens the election.
            rows = self.runner.read_rows(
                self.daemon.idx, gen, end,
                min(shard_end,
                    end + self.runner.DEEP_DEPTH * self.runner.batch),
                window=shard_end - end > self.runner.batch)
            if not rows:
                return
            appended = 0
            for e in rows:
                if e.term != term or e.idx != node.log.end \
                        or node.log.near_full(1):
                    # near_full (not is_full): device drains must not
                    # consume the HEAD-entry reserve, or a filled host
                    # log could never be pruned; rows resume at
                    # log.end once pruning frees space.
                    break
                node.log.write(e)
                appended += 1
            self.stats["drained"] += appended
            if appended == 0:
                return

    # -- follower half ----------------------------------------------------

    def _follower_step(self, node) -> bool:
        """Drain device rows from our shard into the host log (safety
        argument 2: only on top of a current-term entry).  Never touches
        commit — that arrives via the leader's TCP writes."""
        if not self.runner.covers_replica(self.daemon.idx):
            return False       # outside the device geometry/clique
        gen = self.runner.generation
        if gen == 0:
            return False
        key = (gen, self.runner.stats["rounds"])
        if key == self._drain_idle_key:
            return False               # nothing new since the last look
        with self.daemon.lock:
            if node.is_leader:
                return False
            term = node.current_term
            end = node.log.end
            prev = node.log.get(end - 1)
            if prev is None or prev.term != term:
                return False
        shard_end = self.runner.shard_end(self.daemon.idx, gen)
        if shard_end is None or shard_end <= end:
            self._drain_idle_key = key
            return False
        # Bulk drain: one windowed gather when the backlog covers more
        # than a batch (a deep dispatch lands DEEP_DEPTH*B rows at
        # once; draining them one batch-gather at a time costs
        # DEEP_DEPTH device round trips per window).
        rows = self.runner.read_rows(
            self.daemon.idx, gen, end,
            min(shard_end, end + self.runner.DEEP_DEPTH * self.runner.batch),
            window=shard_end - end > self.runner.batch)
        if not rows:
            self._drain_idle_key = key
            return False
        appended = 0
        with self.daemon.lock:
            if node.is_leader or node.current_term != term:
                return False
            for e in rows:
                if e.term != term or e.idx != node.log.end \
                        or node.log.near_full(1):
                    # near_full (not is_full): device drains must not
                    # consume the HEAD-entry reserve, or a filled host
                    # log could never be pruned; rows resume at
                    # log.end once pruning frees space.
                    break
                node.log.write(e)
                appended += 1
        self.stats["drained"] += appended
        return appended > 0
