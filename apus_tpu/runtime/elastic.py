"""Elastic groups: online SPLIT/MERGE of the Multi-Raft keyspace.

PR 10's shards are static — fixed count, fixed key->group hash.  This
module makes the group plane ELASTIC: the keyspace is quantized into
``router.NBUCKETS`` hash buckets, a versioned :class:`router.ShardMap`
assigns buckets to groups, and whole bucket sets migrate between groups
online, under load, with every decision FENCED by replicated records in
the participating groups' own logs ("Reconfigurable Atomic Transaction
Commit"'s discipline: a reconfiguration decision must survive the
failure of whoever drove it).

Protocol (three replicated records; see models/kvs.py for encodings):

    MB  (src group's log)   freeze the bucket set.  From MB-apply on,
        every replica of src deterministically NO-OPS writes into those
        buckets with a REFUSED sentinel (admission refuses them up
        front with a typed MIGRATING answer; the sentinel covers
        entries that raced a leader change past an unapplied MB).
        Because SM apply order == log order, ANY capture taken after
        MB applies is stable — there is nothing a resumed driver can
        miss.
    MI  (dst group's log)   install the captured pairs.  Idempotent by
        mig_id: a driver resumed on a new src leader re-captures
        (bit-identical — frozen) and re-installs harmlessly.
    MC  (src group's log)   commit: delete the moved keys at src, flip
        bucket ownership to dst, bump the shard-map epoch.

Single-ownership invariant: src owns a bucket until MB applies
(refusing writes from then on), NOBODY completes a write in
[MB-apply, MC-apply), and dst owns it from MC-apply on.  Every daemon
hosts a replica of BOTH groups, so each daemon's ownership view
(:meth:`ElasticPlane.shard_map`) is derived locally from its applied
SMs — the same source restart replay and snapshot catch-up rebuild.

Exactly-once across the flip WITHOUT moving the endpoint DB: a write
refused at src (frozen/departed) provably never applied there, so the
client re-routes it under a FRESH req_id and the dst group executes it
once; a write that DID apply at src pre-freeze keeps answering from
src's retained dedup cache.  Monotone per-(client, group) req_id
streams are preserved on both sides — the dedup-merge hazards of
shipping epdb state across groups never arise (DESIGN.md "Elastic
groups" walks the counterexample).

The DRIVER is a per-daemon watchdog thread: whichever daemon currently
leads a group with an open (frozen) migration drives/resumes it — a
leader kill mid-migration just moves the driver with the leadership.

Clients learn the map lazily: a server answering an op for a bucket it
does not own replies with a typed WRONG_GROUP hint carrying the new
epoch AND the full map, so one bounce re-synchronizes a stale-epoch
client.
"""

from __future__ import annotations

import secrets
import socket
import threading
import time
from typing import Optional

from apus_tpu.core.cid import Cid, CidState
from apus_tpu.parallel import wire
from apus_tpu.runtime.router import ShardMap, bucket_of_key

#: admin/control ops on the daemon's PeerServer (top-level, never
#: group-wrapped: the payload names the group it operates on)
OP_SPLIT = 27      # u8 src_gid -> split half of src's buckets into a
                   # NEW group (the leader of src commits MB)
OP_MERGE = 28      # u8 src_gid | u8 dst_gid -> migrate ALL of src's
                   # buckets into dst (src keeps running, owns nothing)
OP_GCTL = 29       # u8 gid | cid -> ensure consensus group gid exists
                   # on this daemon (idempotent; driver broadcast)
OP_SHARDMAP = 30   # -> current shard map + group count

#: cap on dynamically-created groups (gid is a wire u8; 64 is far past
#: any box this runs on)
MAX_GROUPS = 64


class ElasticPlane:
    """Per-daemon elastic-group state: the derived shard map, the
    admission fence, and the migration driver.  Attached by the daemon
    when the multi-group runtime is built (``daemon.elastic``); all map
    reads/recomputes run under the daemon lock."""

    def __init__(self, daemon):
        self.daemon = daemon
        self.base_groups = max(1, getattr(daemon.spec, "groups", 1))
        #: set by the upcall drains whenever a migration record applied
        #: (or a snapshot install may have changed SM migration state);
        #: the next map read recomputes.
        self.dirty = True
        #: False until any migration exists — the admission fast path
        #: is one attribute read on clusters that never migrate.
        self.active = False
        self._map = ShardMap.initial(self.base_groups)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._driver_clt = None
        # Driver-submitted records ride the normal client-write path
        # with a plane-owned client identity (epdb dedup for driver
        # retries); one monotone counter covers every group's stream.
        self._sys_clt = secrets.randbits(62) | (1 << 62)
        self._sys_req = 0
        self._sys_lock = threading.Lock()

    def _next_req(self) -> int:
        with self._sys_lock:
            self._sys_req += 1
            return self._sys_req

    # -- derived ownership view --------------------------------------------

    def _nodes(self):
        d = self.daemon
        if d.groupset is not None:
            return list(enumerate(d.groupset.nodes))
        return [(0, d.node)]

    def _recompute(self) -> None:
        migs = []
        any_open = False
        for _gid, n in self._nodes():
            sm = n.sm
            for rec in getattr(sm, "migs_out", {}).values():
                dst, epoch, state, buckets = rec[:4]
                if state == "committed":
                    migs.append((epoch, tuple(buckets), dst))
                else:
                    any_open = True
            if getattr(sm, "migs_in", None):
                any_open = True
        m = ShardMap.initial(self.base_groups)
        for epoch, buckets, dst in sorted(migs):
            m = m.move(buckets, dst, epoch)
        self._map = m
        self.active = bool(migs) or any_open
        self.dirty = False

    def shard_map(self) -> ShardMap:
        """Current bucket->group assignment, derived from the applied
        SMs (caller holds the daemon lock)."""
        if self.dirty:
            self._recompute()
        return self._map

    def ensure_from_begin(self, data: bytes) -> None:
        """MB applied in a local group (upcall drain, under the daemon
        lock): create the dst group HERE from the record's REPLICATED
        genesis cid — every daemon of the src group applies the same
        bytes, so genesis configurations cannot diverge.  The driver's
        GCTL broadcast remains the catch-up path for daemons that were
        down through the apply."""
        from apus_tpu.models.kvs import decode_mig_begin
        try:
            _mig, dst, _epoch, size, mask, _buckets = \
                decode_mig_begin(data)
        except Exception:                             # noqa: BLE001
            return
        if not size or self.daemon.groupset is None \
                or dst < self.daemon.n_groups or dst >= MAX_GROUPS:
            return
        self._ensure_local(dst, Cid(epoch=0, state=CidState.STABLE,
                                    size=size, new_size=0,
                                    bitmask=mask))

    def genesis_cid_for(self, gid: int) -> "Cid | None":
        """Genesis cid of a split-born group, recovered from the MB
        record in the (already-replayed) src group's SM — the boot
        store-scan path (caller holds the daemon lock or runs at
        construction)."""
        for _g, n in self._nodes():
            for rec in getattr(n.sm, "migs_out", {}).values():
                if rec[0] == gid and len(rec) > 5 and rec[4]:
                    return Cid(epoch=0, state=CidState.STABLE,
                               size=rec[4], new_size=0,
                               bitmask=rec[5])
        return None

    # -- admission fence (client.py handlers, under the daemon lock) ------

    def admit(self, node, data: bytes):
        """Ownership check for a client op against group ``node.gid``:
        None = serve; ("wrong_group", owner_gid) = typed bounce with
        the map; ("migrating",) = bucket frozen mid-migration, client
        retries shortly.  Reads on FROZEN buckets serve (values cannot
        change anywhere until the flip; the reply-time ``departed``
        re-check guards the flip itself).  Multi-key commands (TM
        batches, TP prepares) check EVERY key — the whole command is
        admitted only where every key is owned."""
        if self.dirty:
            self._recompute()
        if not self.active:
            return None
        from apus_tpu.models.kvs import (RESERVED_PREFIX, cmd_is_read,
                                         decode_keys)
        keys = decode_keys(data)
        if not keys:
            return None
        is_read = cmd_is_read(data)
        frozen = getattr(node.sm, "_frozen", ())
        for key in keys:
            if key.startswith(RESERVED_PREFIX):
                continue
            b = bucket_of_key(key)
            owner = self._map.assign[b]
            if owner != node.gid:
                node.bump("wrong_group_hints")
                return ("wrong_group", owner)
            if not is_read and b in frozen:
                node.bump("migrating_refusals")
                return ("migrating",)
        return None

    def departed(self, node, data: bytes) -> "tuple | None":
        """Reply-time read re-check: ("wrong_group", owner) when the
        key's bucket left this node's group while the read was parked
        (serving the locally-applied value would be a stale read past
        the flip); None to serve.  Caller holds the daemon lock."""
        if self.dirty:
            self._recompute()
        if not self.active:
            return None
        from apus_tpu.models.kvs import RESERVED_PREFIX, decode_keys
        keys = decode_keys(data)
        for key in keys or ():
            if key.startswith(RESERVED_PREFIX):
                continue
            owner = self._map.assign[bucket_of_key(key)]
            if owner != node.gid:
                node.bump("wrong_group_hints")
                return ("wrong_group", owner)
        return None

    # -- status / scrape ----------------------------------------------------

    def migrations_view(self) -> list:
        """OP_STATUS: every migration record any local SM knows, with
        its state (caller holds the daemon lock)."""
        out = []
        for gid, n in self._nodes():
            for mid, rec in getattr(n.sm, "migs_out", {}).items():
                out.append({"mig": int(mid), "src": gid, "dst": rec[0],
                            "epoch": rec[1], "state": rec[2],
                            "buckets": len(rec[3])})
        return out

    # -- migration driver ---------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(target=self._run, daemon=True,
                             name=f"apus-elastic-{self.daemon.idx}")
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._driver_clt is not None:
            try:
                self._driver_clt.close()
            except OSError:
                pass

    def _run(self) -> None:
        probe_at = 0.0
        while not self._stop.wait(0.05):
            try:
                self._pass()
            except Exception:                         # noqa: BLE001
                self.daemon.logger.exception(
                    "elastic driver pass failed")
            now = time.monotonic()
            if now >= probe_at:
                probe_at = now + 2.0
                try:
                    self._learn_groups()
                except Exception:                     # noqa: BLE001
                    pass

    def _pass(self) -> None:
        """Resume every open migration whose SRC group this daemon
        currently leads (leader kill mid-migration moves the driver
        with the leadership; every step below is idempotent)."""
        d = self.daemon
        work = []
        with d.lock:
            for gid, node in self._nodes():
                if not node.is_leader:
                    continue
                for mid, rec in getattr(node.sm, "migs_out",
                                        {}).items():
                    if rec[2] == "frozen":
                        work.append((gid, node, int(mid), rec[0],
                                     rec[1], list(rec[3]),
                                     rec[4] if len(rec) > 4 else 0,
                                     rec[5] if len(rec) > 5 else 0))
        for gid, node, mig_id, dst, epoch, buckets, csize, cmask \
                in work:
            if self._stop.is_set():
                return
            self._drive(gid, node, mig_id, dst, epoch, buckets,
                        csize, cmask)

    def _drive(self, gid: int, node, mig_id: int, dst: int,
               epoch: int, buckets: list, csize: int = 0,
               cmask: int = 0) -> None:
        from apus_tpu.models.kvs import (RESERVED_PREFIX,
                                         encode_mig_commit,
                                         encode_mig_install)
        d = self.daemon
        # 1. The dst group must exist on every daemon (idempotent
        # re-broadcast each pass: a peer that was down during the
        # split learns it here or via its own _learn_groups probe).
        # The genesis cid is the one REPLICATED in the MB record —
        # never a locally-projected member set, which could diverge
        # across daemons at the same epoch with no reconciliation.
        with d.lock:
            cid = (Cid(epoch=0, state=CidState.STABLE, size=csize,
                       new_size=0, bitmask=cmask)
                   if csize else _stable_projection(node.cid))
            self._ensure_local(dst, cid)
        payload = wire.u8(OP_GCTL) + wire.u8(dst) + wire.encode_cid(cid)
        for i, addr in enumerate(d.spec.peers):
            if addr and i != d.idx:
                _oneshot(addr, payload, timeout=2.0)
        # 2. Capture the frozen range (stable from MB-apply on — see
        # module docstring; any two captures are identical).
        with d.lock:
            if not node.is_leader:
                return
            bset = set(buckets)
            pairs = [(k, v) for k, v in node.sm.store.items()
                     if not k.startswith(RESERVED_PREFIX)
                     and bucket_of_key(k) in bset]
        if d.obs is not None:
            d.obs.flight.note("elastic", "capture", gid=gid,
                              mig=mig_id, dst=dst, keys=len(pairs))
        # 3. Install at dst, 4. commit at src — both through the
        # ordinary replicated client-write path (the records are
        # majority-acked in their group before the driver proceeds;
        # MI is idempotent by mig_id, MC by state).
        if not self._group_write(
                dst, encode_mig_install(mig_id, gid, epoch, buckets,
                                        pairs)):
            return                       # retried on the next pass
        if not self._group_write(gid, encode_mig_commit(mig_id)):
            return
        node.bump("migrations")
        if d.obs is not None:
            d.obs.flight.note("elastic", "committed", gid=gid,
                              mig=mig_id, dst=dst, epoch=epoch)
        d.logger.info("elastic: migration %d committed — %d buckets "
                      "g%d -> g%d (router epoch %d)", mig_id,
                      len(buckets), gid, dst, epoch)
        with d.lock:
            self.dirty = True

    def _ensure_local(self, gid: int, cid: Cid) -> None:
        """Create missing groups up to ``gid`` on THIS daemon (caller
        holds the daemon lock)."""
        d = self.daemon
        if d.groupset is None:
            raise RuntimeError("elastic groups need the multi-group "
                               "runtime (spec.groups >= 2)")
        while d.n_groups <= gid:
            d.groupset.ensure_group(d.n_groups, cid)
            self.dirty = True

    def _group_write(self, gid: int, data: bytes,
                     timeout: float = 15.0) -> bool:
        from apus_tpu.runtime.client import OP_CLT_WRITE, ApusClient
        c = self._driver_clt
        if c is None:
            c = ApusClient([p for p in self.daemon.spec.peers if p],
                           clt_id=self._sys_clt, timeout=timeout,
                           attempt_timeout=3.0)
            self._driver_clt = c
        try:
            rid = self._next_req()
            c._req_seq = rid
            reply = c._op(OP_CLT_WRITE, rid, data, gid=gid)
            return reply == b"OK"
        except (TimeoutError, RuntimeError, OSError, ConnectionError):
            return False

    def _learn_groups(self) -> None:
        """A daemon that missed a split (down while it happened) learns
        the new groups from any peer's status and creates them locally
        with the peer's reported configuration — the per-group catch-up
        replication then fills its log."""
        from apus_tpu.runtime.client import probe_status
        d = self.daemon
        if d.groupset is None:
            return
        for i, addr in enumerate(d.spec.peers):
            if not addr or i == d.idx:
                continue
            st = probe_status(addr, timeout=0.5)
            if st is None:
                continue
            theirs = st.get("n_groups", 1)
            if theirs <= d.n_groups:
                return
            for gid in range(d.n_groups, min(theirs, MAX_GROUPS)):
                gv = (st.get("groups") or {}).get(str(gid))
                if gv is None:
                    continue
                members = gv.get("members", [])
                cid = Cid(epoch=gv.get("epoch", 0),
                          state=CidState.STABLE, size=len(members),
                          new_size=0,
                          bitmask=sum(1 << m for m in members))
                with d.lock:
                    if d.n_groups == gid:
                        d.groupset.ensure_group(gid, cid)
                        self.dirty = True
                d.logger.info("elastic: learned group %d from %s",
                              gid, addr)
            return


def _stable_projection(cid: Cid) -> Cid:
    """The src group's CURRENT member set as a fresh STABLE cid — the
    genesis configuration of a split's new group (same daemons, own
    epochs from 0)."""
    return Cid(epoch=0, state=CidState.STABLE,
               size=cid.extended_group_size, new_size=0,
               bitmask=cid.bitmask)


# -- daemon-side admin ops --------------------------------------------------

def make_elastic_ops(daemon) -> dict:
    from apus_tpu.runtime.client import _not_leader
    from apus_tpu.runtime.membership import ST_REFUSED, ST_RETRY

    plane = daemon.elastic

    def _refused(reason: bytes, transient: bool = False) -> bytes:
        return (wire.u8(ST_RETRY if transient else ST_REFUSED)
                + wire.blob(reason))

    def _start(src: int, dst_req: "int | None") -> bytes:
        from apus_tpu.models.kvs import encode_mig_begin
        node = daemon.group_node(src)
        if node is None:
            return _refused(b"unknown_src_group")
        with daemon.lock:
            if not node.is_leader:
                return _not_leader(daemon, node=node)
            if daemon.groupset is None:
                return _refused(b"single_group_daemon")
            m = plane.shard_map()
            owned = m.owned(src)
            for rec in node.sm.migs_out.values():
                if rec[2] == "frozen":
                    return _refused(b"migration_in_flight",
                                    transient=True)
            if dst_req is None:
                if len(owned) < 2:
                    return _refused(b"too_few_buckets")
                # Prefer an EXISTING empty dynamic group over a fresh
                # gid: a split whose MB raced a txn write-lock (apply-
                # time REFUSED, retried) has already created its dst
                # locally — always allocating anew leaked one orphan
                # group per refused attempt (trial 28101: nine groups
                # where eight belonged).  Empty = owns no buckets and
                # is not the dst of an in-flight (frozen) migration;
                # merged-away groups qualify too (bucket return is a
                # supported ownership chain).
                static_n = max(1, int(getattr(daemon.spec, "groups",
                                              1) or 1))
                busy = {rec[0] for _g, n2 in plane._nodes()
                        for rec in getattr(n2.sm, "migs_out",
                                           {}).values()
                        if rec[2] == "frozen"}
                dst = next(
                    (g for g in range(static_n, daemon.n_groups)
                     if not m.owned(g) and g not in busy),
                    daemon.n_groups)
                if dst >= MAX_GROUPS:
                    return _refused(b"group_cap")
                buckets = ShardMap.split_buckets(owned)
            else:
                dst = dst_req
                if dst == src or dst >= daemon.n_groups:
                    return _refused(b"bad_dst_group")
                if not owned:
                    return _refused(b"src_owns_nothing")
                buckets = owned
            locks = getattr(node.sm, "_locks", None)
            if locks:
                # Open prepared transaction write-locking a key in the
                # bucket set: the freeze must wait (submit-time check,
                # BEFORE the dst group is created — the apply-time
                # REFUSED in models/kvs.py stays as the backstop for
                # entries that raced a leader change, but refusing
                # here avoids allocating an orphan dst gid per retry).
                bset = set(buckets)
                for k, lk in locks.items():
                    if lk[1] == "w" and bucket_of_key(k) in bset:
                        return _refused(b"txn_locked", transient=True)
            epoch = m.epoch + 1
            mig_id = (epoch << 8) | src
            csize = cmask = 0
            if dst_req is None:
                # SPLIT: decide the new group's genesis configuration
                # ONCE (the src group's member set now) and replicate
                # it inside MB — every daemon then creates the group
                # from the same bytes at MB-apply.
                gcid = _stable_projection(node.cid)
                csize, cmask = gcid.size, gcid.bitmask
                plane._ensure_local(dst, gcid)
            pr = node.submit(plane._next_req(), plane._sys_clt,
                             encode_mig_begin(mig_id, dst, epoch,
                                              buckets, csize, cmask))
            if pr is None:
                return _not_leader(daemon, node=node)
            node.flush_pending()
        if daemon.obs is not None:
            daemon.obs.flight.note("elastic", "begin", gid=src,
                                   mig=mig_id, dst=dst, epoch=epoch,
                                   buckets=len(buckets))
        deadline = time.monotonic() + daemon.client_op_timeout
        with daemon.commit_cond:
            while True:
                if pr.reply is not None:
                    from apus_tpu.models.sm import REFUSED_REPLY_PREFIX
                    if pr.reply.startswith(REFUSED_REPLY_PREFIX):
                        # MB deferred: a write-locked key (open
                        # prepared transaction) sits in the bucket set
                        # — the freeze must wait for the txn to
                        # resolve (models/kvs.py MB apply).  Transient
                        # typed refusal; request_split retries.
                        return _refused(b"txn_locked", transient=True)
                    return (wire.u8(wire.ST_OK) + wire.u64(mig_id)
                            + wire.u8(dst) + wire.u32(epoch))
                if not node.is_leader:
                    return _not_leader(daemon, node=node)
                left = deadline - time.monotonic()
                if left <= 0:
                    return _refused(b"begin_timeout", transient=True)
                daemon.commit_cond.wait(min(left, 0.25))

    def split(r: wire.Reader) -> bytes:
        return _start(r.u8(), None)

    def merge(r: wire.Reader) -> bytes:
        return _start(r.u8(), r.u8())

    def gctl(r: wire.Reader) -> bytes:
        gid = r.u8()
        cid = wire.decode_cid(r)
        if gid >= MAX_GROUPS:
            return wire.u8(wire.ST_ERROR)
        with daemon.lock:
            if daemon.groupset is None:
                return wire.u8(wire.ST_ERROR)
            try:
                plane._ensure_local(gid, cid)
            except RuntimeError:
                return wire.u8(wire.ST_ERROR)
        return wire.u8(wire.ST_OK)

    def shardmap(r: wire.Reader) -> bytes:
        with daemon.lock:
            m = plane.shard_map()
        return (wire.u8(wire.ST_OK) + wire.blob(m.to_blob())
                + wire.u8(daemon.n_groups))

    return {OP_SPLIT: split, OP_MERGE: merge, OP_GCTL: gctl,
            OP_SHARDMAP: shardmap}


# -- operator/harness side --------------------------------------------------

def _oneshot(addr: str, payload: bytes,
             timeout: float = 2.0) -> Optional[bytes]:
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(timeout)
            conn.sendall(wire.frame(payload))
            return wire.read_frame(conn)
    except (OSError, ConnectionError, ValueError):
        return None


def _request_mig(peers: list, payload: bytes, what: str,
                 timeout: float) -> dict:
    """Find the src group's leader and start the migration; returns
    {mig, dst, epoch} once MB committed."""
    from apus_tpu.runtime.client import ST_NOT_LEADER
    from apus_tpu.runtime.membership import (ST_REFUSED, ST_RETRY,
                                             _Backoff)
    import random as _random
    deadline = time.monotonic() + timeout
    cands = [p for p in peers if p]
    backoff = _Backoff(_random.Random())
    i = 0
    while time.monotonic() < deadline:
        target = cands[i % len(cands)]
        i += 1
        resp = _oneshot(target, payload,
                        timeout=max(0.2, min(6.0,
                                             deadline
                                             - time.monotonic())))
        if resp is None:
            backoff.sleep(deadline)
            continue
        st = resp[0]
        if st == wire.ST_OK:
            r = wire.Reader(resp[1:])
            return {"mig": r.u64(), "dst": r.u8(), "epoch": r.u32()}
        if st == ST_NOT_LEADER:
            hint = wire.Reader(resp[1:]).blob().decode() \
                if len(resp) > 1 else ""
            if hint and hint not in cands:
                cands.append(hint)
            if hint:
                i = cands.index(hint)
                backoff.reset()
            time.sleep(0.01)
            continue
        if st == ST_REFUSED:
            reason = wire.Reader(resp[1:]).blob().decode()
            raise RuntimeError(f"{what} refused: {reason}")
        if st == ST_RETRY:
            backoff.sleep(deadline)
            continue
        backoff.sleep(deadline)
    raise TimeoutError(f"{what} not started within {timeout}s")


def request_split(peers: list, src_gid: int,
                  timeout: float = 30.0) -> dict:
    """Start a SPLIT of ``src_gid`` into a new group.  Returns
    {mig, dst, epoch} once the freeze record (MB) committed; poll
    :func:`wait_router_epoch` for completion."""
    return _request_mig(peers, wire.u8(OP_SPLIT) + wire.u8(src_gid),
                        f"split of group {src_gid}", timeout)


def request_merge(peers: list, src_gid: int, dst_gid: int,
                  timeout: float = 30.0) -> dict:
    """Start a MERGE of all of ``src_gid``'s buckets into
    ``dst_gid``."""
    return _request_mig(peers,
                        wire.u8(OP_MERGE) + wire.u8(src_gid)
                        + wire.u8(dst_gid),
                        f"merge g{src_gid} -> g{dst_gid}", timeout)


def fetch_shard_map(addr: str, timeout: float = 2.0):
    """(ShardMap, n_groups) from one daemon, or None."""
    resp = _oneshot(addr, wire.u8(OP_SHARDMAP), timeout=timeout)
    if not resp or resp[0] != wire.ST_OK:
        return None
    r = wire.Reader(resp[1:])
    m = ShardMap.from_blob(r.blob())
    n = r.u8() if r.remaining else m.n_groups
    return m, n


def wait_router_epoch(peers: list, epoch: int,
                      timeout: float = 60.0) -> None:
    """Block until EVERY reachable daemon reports shard-map epoch >=
    ``epoch`` (the migration committed and the flip propagated to all
    members' local views)."""
    deadline = time.monotonic() + timeout
    last: list = []
    while time.monotonic() < deadline:
        views = []
        for addr in [p for p in peers if p]:
            got = fetch_shard_map(addr, timeout=1.0)
            if got is not None:
                views.append(got[0].epoch)
        last = views
        if views and all(v >= epoch for v in views):
            return
        time.sleep(0.05)
    raise AssertionError(
        f"router epoch {epoch} never reached all members within "
        f"{timeout}s (saw {last})")
