"""Follower linearizable reads: the lease wire exchange (OP_FLR_LEASE).

Protocol glue for NodeConfig.follower_read_leases (the lease state
machine itself lives in core/node.py):

- ``make_flr_ops`` registers the LEADER side on a daemon's PeerServer:
  one two-sided control op answering a follower's lease request with a
  grant (term, config epoch, commit floor, duration) or a typed
  refusal.  Runs under the daemon lock; no wire ops inside.
- ``install_flr`` installs the FOLLOWER side: a ``Node.lease_requester``
  callable that performs one bounded request/response roundtrip through
  the daemon's transport — which both yields the node lock on the wire
  AND routes through the fault plane when one is armed, so lease
  traffic is attackable (dropped, delayed, partitioned) like every
  other control message.

Anchoring contract (the part that keeps adversarial time out): the
requester stamps its fresh clock BEFORE the roundtrip and anchors the
granted duration there (Node._request_flease); the granter's
conservative window is anchored at its receipt.  Send precedes receipt
in real time, so the granter's tracking window always outlives the
holder's belief, with rate drift absorbed by the lease margin.

The deterministic simulator never installs a requester, so sim nodes
stay wire-free and clock-pure.
"""

from __future__ import annotations

from apus_tpu.parallel import wire
from apus_tpu.runtime.router import NBUCKETS

#: PeerServer extra-op byte (after OP_OBS_DUMP=23).
OP_FLR_LEASE = 24

#: Read-set bitmap length: one bit per shard-map bucket (840/8).
BITMAP_BYTES = (NBUCKETS + 7) // 8


def buckets_to_bitmap(buckets) -> bytes:
    """Frozenset of buckets -> the request's 105-byte bitmap."""
    bm = bytearray(BITMAP_BYTES)
    for b in buckets:
        bm[b >> 3] |= 1 << (b & 7)
    return bytes(bm)


def bitmap_to_buckets(bm: bytes) -> "frozenset[int]":
    return frozenset(b for b in range(NBUCKETS)
                     if bm[b >> 3] & (1 << (b & 7)))


def _request_payload(idx: int, incarnation: int, want) -> bytes:
    """OP_FLR_LEASE request body.  ``want`` is the requested read set
    (frozenset of buckets) or None = FULL set; full-set requests omit
    the bitmap entirely — byte-identical to the pre-bucket wire shape,
    and an old leader ignoring the trailer simply grants whole-log."""
    payload = (wire.u8(OP_FLR_LEASE) + wire.u8(idx)
               + wire.u32(incarnation))
    if want is not None:
        payload += buckets_to_bitmap(want)
    return payload


def _parse_grant(resp) -> "dict | None":
    if not resp or resp[0] != wire.ST_OK or len(resp) < 33:
        return None
    rr = wire.Reader(resp[1:])
    return {"term": rr.u64(), "epoch": rr.u64(),
            "floor": rr.u64(), "dur": rr.u64() / 1e6}


def make_flr_ops(daemon, node=None) -> dict:
    """Leader-side lease grant op for a ReplicaDaemon's PeerServer.
    ``node`` binds the grant to one consensus group's node (multi-group
    daemons register one per group port); None = the primary group."""
    node = node if node is not None else daemon.node

    def flr_lease(r: wire.Reader) -> bytes:
        peer = r.u8()
        incarnation = r.u32() if r.remaining >= 4 else 0
        # Optional read-set bitmap trailer (bucket-granular leases):
        # absent = full-set request (the pre-bucket wire shape).
        buckets = None
        if r.remaining >= BITMAP_BYTES:
            buckets = bitmap_to_buckets(r.take(BITMAP_BYTES))
        with daemon.lock:
            g = node.grant_follower_lease(
                peer, incarnation=incarnation, buckets=buckets)
        if g is None:
            return wire.u8(wire.ST_REFUSED)
        return (wire.u8(wire.ST_OK) + wire.u64(g["term"])
                + wire.u64(g["epoch"]) + wire.u64(g["floor"])
                + wire.u64(max(0, int(g["dur"] * 1e6))))

    return {OP_FLR_LEASE: flr_lease}


def install_flr(daemon) -> None:
    """Install the follower-side lease requester on ``daemon.node``."""

    def request(leader_idx: int, want=None):
        payload = _request_payload(daemon.idx,
                                   daemon.node.incarnation, want)
        resp = daemon.transport.request(leader_idx, payload)
        return _parse_grant(resp)

    daemon.node.lease_requester = request
