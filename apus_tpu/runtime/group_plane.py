"""Group-major device plane: one dispatch commits MANY groups' windows,
sharded across a real multi-device mesh with async, overlapped windows.

The Multi-Raft payoff on the device (ROADMAP "group-major device
dispatch"): the single-group engine (runtime.device_plane) amortizes
dispatch overhead over ROUNDS of one group; this plane adds the GROUP
axis — a ``GroupDeviceRunner`` owns a group-major devlog
(ops.logplane.GroupDeviceLog, [G, R, ...]) and the group-window step
(ops.commit.build_group_window_step), so one XLA program carries up to
``max_depth`` rounds of up to ``n_groups`` groups' pending windows:
one leader-broadcast pmax, one ack all_gather, one vectorized
dual-majority vote for every group, with per-group early-exit masks
(``GroupCommitControl.rounds``) letting shallow-backlog groups ride a
deep dispatch without paying its rounds.

MULTI-DEVICE (ISSUE 14): the runner builds a 2-D ``(group, replica)``
mesh (ops.mesh.group_replica_mesh — groups sharded across devices,
graceful fold when devices are scarce, ``APUS_DEV_MESH_DEVICES`` caps
the budget) and shards the devlog + staged windows along it, so the
ONE SPMD program runs G groups' windows CONCURRENTLY across devices
instead of timesharing one — the mesh analog of the reference's
passive parallel replication on the NIC.  Groups are mutually
independent (no group-axis collective exists in the step), so
cross-device results are byte-identical to the single-device fold.

ASYNC DISPATCH: ``dispatch_groups`` stages (reusable GroupStagingRing
pair -> sharded device_put -> donated step call) and advances the
per-group cursors WITHOUT waiting on device results;
``adopt_window`` is the ADOPTION FENCE — the only blocking point.
The driver beat dispatches window N+1 before fencing window N, so
host staging for N+1 overlaps device execution of N and commit
adoption is batched per beat (``dev_async_overlap_windows`` counts
the overlapped windows).

``GroupPlaneDriver`` is one thread per daemon serving ALL of its
groups: each driver pass collects every led group's clean window under
the daemon lock, dispatches them as ONE group-major window (the
leader's group-commit drain amortizing one lock + one dispatch across
every group with queued ops), and — at the fence — adopts each
group's device commit under the same safety rules as the single-group
driver:

1. commit chaining — a group's device results are adopted only once
   host commit covered the prefix below that group's device base;
2. follower drain — device rows append only on top of a current-term
   host tail (per group);
3. live-mask honesty — the vote is masked to members whose host
   control-plane writes were recently observed, denominators stay the
   full configuration sizes;
plus the stall watchdog / quorum-fail streak fallbacks, per group.

Telemetry (the acceptance evidence that dispatches are group-major):
``dev_group_major_windows`` counts dispatches, ``dev_groups_per_dispatch``
histograms how many groups each carried, and the recompile sentinel
rides the same process-wide compile ledger as the single-group runner.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from apus_tpu.core.cid import CidState
from apus_tpu.core.quorum import quorum_size
from apus_tpu.core.types import EntryType
from apus_tpu.parallel import wire
from apus_tpu.parallel.transport import Region
from apus_tpu.runtime.device_plane import (_COMPILES, _EXPECTED,
                                           _ensure_compile_listener,
                                           unexpected_compiles)


class GroupDeviceRunner:
    """Process-wide group-major engine, shared by every in-process
    daemon (one devlog, per-group generations/fences)."""

    #: marks this runner for the daemon's driver selection.
    group_major = True

    def __init__(self, n_groups: int, n_replicas: int,
                 n_slots: int = 512, slot_bytes: int = 4096,
                 batch: int = 16, max_depth: int = 4, devices=None,
                 logger=None):
        self.n_groups = n_groups
        self.n_replicas = n_replicas
        self.n_slots = n_slots
        self.slot_bytes = slot_bytes
        self.batch = batch
        self.max_depth = max_depth
        self._devices = devices
        self.logger = logger
        self.lock = threading.Lock()
        #: per-GROUP generation tokens (a group's leadership reset must
        #: not invalidate other groups' in-flight work).
        self.generations = [0] * n_groups
        self._leader = [None] * n_groups
        self._term = [0] * n_groups
        self._next_end0 = [None] * n_groups
        from apus_tpu.obs.metrics import MetricsRegistry
        self.metrics = MetricsRegistry()
        self.stats = self.metrics.view("dev")
        for k in ("rounds", "resets", "quorum_fail_rounds",
                  "entries_devplane", "group_major_windows",
                  "recompiles", "async_overlap_windows"):
            self.stats.setdefault(k, 0)
        self._groups_per_dispatch = self.metrics.histogram(
            "dev_groups_per_dispatch")
        self._groups_per_device = self.metrics.histogram(
            "dev_groups_per_device_max")
        self._dispatch_wait_hist = self.metrics.histogram(
            "dev_dispatch_wait_us")
        self._staging_wait_hist = self.metrics.histogram(
            "dev_staging_wait_us")
        self._max_dispatch = self.metrics.gauge("dev_max_dispatch_ms")
        self._devices_gauge = self.metrics.gauge("dev_devices")
        #: dispatched-but-unadopted windows (under self.lock): >0 at
        #: dispatch time means this window's staging OVERLAPPED the
        #: previous window's device execution — the async-beat win the
        #: critpath tool attributes (dev_async_overlap_windows).
        self._open_windows = 0
        self._built = False
        self._build()

    # -- build + warmup ----------------------------------------------------

    def _build(self) -> None:
        if self._built:
            return
        _ensure_compile_listener()
        compiles_at_start = _COMPILES["count"]
        import os
        import jax
        import jax.numpy as jnp
        import functools

        from apus_tpu.ops.commit import build_group_window_step
        from apus_tpu.ops.logplane import (GroupDeviceLog,
                                           GroupStagingRing,
                                           make_group_device_log)
        from apus_tpu.ops.mesh import (GROUP_AXIS, group_replica_mesh,
                                       group_sharding,
                                       group_staged_sharding)

        self._jax = jax
        devices = self._devices
        if devices is None:
            # Default mesh budget: every local device (group-major
            # dispatch shards groups across them); APUS_DEV_MESH_DEVICES
            # caps it (bench ladders pin exact device counts this way,
            # "1" reproduces the pre-multi-device single-chip fold).
            cap = int(os.environ.get("APUS_DEV_MESH_DEVICES", "0") or 0)
            devices = jax.devices()
            if cap > 0:
                devices = devices[:cap]
        self._mesh = group_replica_mesh(self.n_groups, self.n_replicas,
                                        devices=devices)
        self.group_axis_size = int(self._mesh.shape[GROUP_AXIS])
        self.n_devices = int(np.prod(list(self._mesh.shape.values())))
        #: contiguous block of groups per device shard along the group
        #: axis (group g lives on shard row ``g // groups_per_shard``).
        self.groups_per_shard = self.n_groups // self.group_axis_size
        self._devices_gauge.set(self.n_devices)
        self._sharding = group_sharding(self._mesh)
        self._staged_sharding = group_staged_sharding(self._mesh)
        self._staging = GroupStagingRing(
            self.max_depth, self.n_groups, self.n_replicas, self.batch,
            self.slot_bytes)
        self._staging.wait_hist = self._staging_wait_hist
        self._step = build_group_window_step(
            self._mesh, self.n_groups, self.n_replicas, self.n_slots,
            self.slot_bytes, self.batch, self.max_depth)
        # Follower shard readers (one batch / one window of rows).
        self._gather = jax.jit(lambda d, m, g, r, s: (d[g, r, s],
                                                      m[g, r, s]))
        self._offs_one = jax.jit(lambda o, g, r: o[g, r])

        @functools.partial(jax.jit, donate_argnums=0)
        def _reset(gl: GroupDeviceLog, g, leader, term, first_idx):
            data = gl.data.at[g].set(0)
            meta = gl.meta.at[g].set(0)
            offs = gl.offs.at[g].set(first_idx)
            fence = gl.fence.at[g].set(
                jnp.stack([leader, term]).astype(jnp.int32))
            return GroupDeviceLog(data, meta, offs, fence)

        self._reset_fn = _reset
        self._devlog = make_group_device_log(
            self.n_groups, self.n_replicas, self.n_slots,
            self.slot_bytes, self.batch, sharding=self._sharding)
        self._warmup()
        _EXPECTED["count"] += _COMPILES["count"] - compiles_at_start
        self._compile_baseline = unexpected_compiles()
        self._built = True

    def _warmup(self) -> None:
        """Compile every live dispatch signature up front — a compile
        racing live traffic is the recompile-sentinel bug class.  Two
        step dispatches (fresh placement, then the donated/device-
        resident signature every later dispatch uses), a reset, and
        both reader shapes."""
        jax, np_ = self._jax, np
        G, R, B, MD, SB = (self.n_groups, self.n_replicas, self.batch,
                          self.max_depth, self.slot_bytes)
        self._devlog = self._reset_fn(self._devlog, np_.int32(0),
                                      np_.int32(0), np_.int32(1),
                                      np_.int32(1))
        sdata = jax.device_put(np_.zeros((MD, G, R, B, SB), np_.uint8),
                               self._staged_sharding)
        smeta = jax.device_put(np_.zeros((MD, G, R, B, 4), np_.int32),
                               self._staged_sharding)
        ctrl = self._make_ctrl(
            [(g, 0, 1, 1, None, set(range(R)), 0) for g in range(G)])
        self._devlog, commits = self._step(self._devlog, sdata, smeta,
                                           ctrl)
        jax.block_until_ready(commits)
        sdata = jax.device_put(np_.zeros((MD, G, R, B, SB), np_.uint8),
                               self._staged_sharding)
        smeta = jax.device_put(np_.zeros((MD, G, R, B, 4), np_.int32),
                               self._staged_sharding)
        self._devlog, commits = self._step(self._devlog, sdata, smeta,
                                           ctrl)
        jax.block_until_ready(commits)
        for n in (B, B * MD):
            jax.block_until_ready(self._gather(
                self._devlog.data, self._devlog.meta, np_.int32(0),
                np_.int32(0), np_.zeros(n, np_.int32)))
        jax.block_until_ready(self._offs_one(self._devlog.offs,
                                             np_.int32(0),
                                             np_.int32(0)))
        # Warm state is throwaway: every group back to a closed fence.
        for g in range(G):
            self._devlog = self._reset_fn(self._devlog, np_.int32(g),
                                          np_.int32(-1), np_.int32(0),
                                          np_.int32(1))

    def check_recompiles(self) -> list:
        """Process-wide recompile sentinel (shared compile ledger with
        the single-group runner): any backend compile past what builds
        and warmups accounted for is a live-path recompile."""
        unexpected = unexpected_compiles()
        delta = unexpected - self._compile_baseline
        if delta <= 0:
            return []
        self._compile_baseline = unexpected
        self.stats.bump("recompiles", delta)
        return [("group_step", 0, 0)]

    # -- sizing contract ---------------------------------------------------

    WIRE_OVERHEAD = 64

    def max_data_bytes(self) -> int:
        return self.slot_bytes - self.WIRE_OVERHEAD

    def covers_replica(self, slot: int) -> bool:
        return 0 <= slot < self.n_replicas

    def quorum_coverable(self, cid) -> bool:
        return cid.extended_group_size <= self.n_replicas

    # -- per-group leadership reset ---------------------------------------

    def reset_group(self, gid: int, leader: int, term: int,
                    first_idx: int) -> Optional[int]:
        """Fresh shard set for group ``gid``'s new leadership; other
        groups' state is untouched.  Stale terms refused (None)."""
        with self.lock:
            if term < self._term[gid]:
                return None
            self.generations[gid] += 1
            self._devlog = self._reset_fn(
                self._devlog, np.int32(gid), np.int32(leader),
                np.int32(term), np.int32(first_idx))
            self._leader[gid], self._term[gid] = leader, term
            self._next_end0[gid] = first_idx
            self.stats.bump("resets")
            if self.logger is not None:
                self.logger.info(
                    "group plane reset: g%d gen=%d leader=%d term=%d "
                    "base=%d", gid, self.generations[gid], leader, term,
                    first_idx)
            return self.generations[gid]

    # -- the group-major dispatch -----------------------------------------

    def _encode_round(self, entries, end0: int, out_data, out_meta):
        B, SB = self.batch, self.slot_bytes
        flat = memoryview(out_data.reshape(-1))
        for j, e in enumerate(entries):
            assert e.idx == end0 + j, (e.idx, end0, j)
            size = wire.entry_wire_size(e)
            if size > SB:
                raise ValueError(f"entry {e.idx} wire size {size} > "
                                 f"slot {SB}; segment upstream")
            wire.encode_entry_into(e, flat, j * SB)
            out_meta[j] = (e.req_id & 0x7FFFFFFF, e.clt_id & 0x7FFFFFFF,
                           int(e.type), size)

    def _make_ctrl(self, items):
        """GroupCommitControl from per-group work items:
        ``items`` = [(gid, leader, term, end0, cid_or_None, live,
        n_rounds)]; groups absent from ``items`` get rounds 0 (masked
        out of every round)."""
        import jax.numpy as jnp

        from apus_tpu.ops.commit import GroupCommitControl
        G, R = self.n_groups, self.n_replicas
        leader = np.full(G, -2, np.int32)
        term = np.zeros(G, np.int32)
        end0 = np.ones(G, np.int32)
        rounds = np.zeros(G, np.int32)
        mask_old = np.zeros((G, R), np.int32)
        mask_new = np.zeros((G, R), np.int32)
        q_old = np.full(G, R + 1, np.int32)
        q_new = np.zeros(G, np.int32)
        for gid, ldr, trm, e0, cid, live, n in items:
            leader[gid], term[gid], end0[gid] = ldr, trm, e0
            rounds[gid] = n
            if cid is None:
                mask_old[gid] = [1 if i in live else 0 for i in range(R)]
                q_old[gid] = quorum_size(R)
                continue
            mask_old[gid] = [
                1 if (cid.contains(i) and i < cid.size and i in live)
                else 0 for i in range(R)]
            q_old[gid] = quorum_size(cid.size)
            if cid.state == CidState.TRANSIT:
                mask_new[gid] = [
                    1 if (cid.contains(i) and i < cid.new_size
                          and i in live) else 0 for i in range(R)]
                q_new[gid] = quorum_size(cid.new_size)
        i32 = lambda v: jnp.asarray(v, jnp.int32)   # noqa: E731
        return GroupCommitControl(i32(leader), i32(term), i32(end0),
                                  i32(rounds), i32(mask_old),
                                  i32(mask_new), i32(q_old), i32(q_new))

    def device_of_group(self, gid: int) -> int:
        """Device-shard row (along the mesh's group axis) that executes
        group ``gid``'s windows — the static block assignment of the
        group-sharded layout."""
        return gid // self.groups_per_shard

    def dispatch_groups(self, work: list) -> Optional["_InFlightWindow"]:
        """Stage + enqueue ONE group-major dispatch WITHOUT waiting for
        its device results.  ``work`` = [(gid, gen, end0, entries, cid,
        live)] with ``len(entries) = n_g * batch``, 1 <= n_g <=
        max_depth, entries idx-contiguous from end0.

        The per-group cursors (``_next_end0``) advance at DISPATCH, so
        the driver's next collection pass chains window N+1 on top of
        window N while N still executes — the async overlap beat.  The
        only blocking edge on this path is the staging ring's consumer
        edge (a buffer pair is not rewritten until the transfer that
        read it completed); device results are fenced later, in
        ``adopt_window``.  Returns the in-flight handle, or None when
        nothing was dispatchable (every item's generation/cursor moved
        between collection and dispatch)."""
        B, MD = self.batch, self.max_depth
        with self.lock:
            live_work = []
            for gid, gen, end0, entries, cid, live in work:
                if gen != self.generations[gid] \
                        or end0 != self._next_end0[gid]:
                    continue
                live_work.append((gid, gen, end0, entries, cid, live))
            if not live_work:
                return None
        # Host staging with the runner lock released (encode is the
        # slow part); leader-row-only expansion host-side (CPU-backend
        # deployment; mirrors place_batch's rationale).  The ring pair
        # is reused window over window — acquire blocks only on the
        # consumer edge of the pair's previous transfer.
        slot = self._staging.acquire()
        sdata, smeta = slot.data, slot.meta
        items = []
        for gid, gen, end0, entries, cid, live in live_work:
            n = len(entries) // B
            assert 1 <= n <= MD and len(entries) == n * B, \
                (gid, len(entries), n)
            with self.lock:
                ldr, trm = self._leader[gid], self._term[gid]
            for k in range(n):
                self._encode_round(entries[k * B:(k + 1) * B],
                                   end0 + k * B,
                                   sdata[k, gid, ldr],
                                   smeta[k, gid, ldr])
            items.append((gid, ldr, trm, end0, cid, live, n))
        ctrl = self._make_ctrl(items)
        jd = self._jax.device_put(sdata, self._staged_sharding)
        jm = self._jax.device_put(smeta, self._staged_sharding)
        self._staging.staged(slot, (jd, jm))
        with self.lock:
            # Re-validate under the lock right before the (donating)
            # step: a reset that raced the staging discards this work.
            final = []
            for (gid, gen, end0, _e, _c, _lv), it in zip(live_work,
                                                         items):
                if gen != self.generations[gid] \
                        or end0 != self._next_end0[gid]:
                    continue
                final.append(it)
            if not final:
                return None
            if len(final) != len(items):
                # Somebody reset mid-staging: rebuild ctrl with the
                # stale groups masked out (rounds 0 — they write into
                # scratch and report 0).
                ctrl = self._make_ctrl(final)
            self._devlog, commits = self._step(self._devlog, jd, jm,
                                               ctrl)
            total_rounds = 0
            shard_load: dict[int, int] = {}
            for gid, _l, _t, end0, _c, _lv, n in final:
                self._next_end0[gid] = end0 + n * B
                total_rounds += n
                row = self.device_of_group(gid)
                shard_load[row] = shard_load.get(row, 0) + 1
            self.stats.bump("rounds", total_rounds)
            self.stats.bump("entries_devplane", total_rounds * B)
            self.stats.bump("group_major_windows")
            if self._open_windows > 0:
                self.stats.bump("async_overlap_windows")
            self._open_windows += 1
            self._groups_per_dispatch.observe(len(final))
            # Busiest device shard this dispatch: 1 means the window's
            # groups spread perfectly across the mesh; == len(final)
            # means they all landed on one device (the 1-device fold).
            self._groups_per_device.observe(max(shard_load.values()))
            gens = {it[0]: self.generations[it[0]] for it in final}
        return _InFlightWindow(items=final, commits=commits, gens=gens)

    def adopt_window(self, win: "_InFlightWindow") -> dict:
        """The ADOPTION FENCE: block until ``win``'s device commits are
        host-readable and fold them into {gid: device_commit}, dropping
        any group whose generation moved since dispatch.  This is the
        only ``block_until_ready``-equivalent on the async critical
        path."""
        B = self.batch
        t0 = time.monotonic()
        commits_host = np.asarray(win.commits)      # [MD, G]
        wait = time.monotonic() - t0
        self._dispatch_wait_hist.observe(int(wait * 1e6))
        if wait * 1e3 > self._max_dispatch.value:
            self._max_dispatch.set(wait * 1e3)
        out = {}
        with self.lock:
            self._open_windows = max(0, self._open_windows - 1)
            for gid, _l, _t, end0, _c, _lv, n in win.items:
                if self.generations[gid] != win.gens[gid]:
                    continue                 # reset since dispatch
                commit = int(commits_host[n - 1, gid])
                qf = sum(int(commits_host[k, gid]) < end0 + (k + 1) * B
                         for k in range(n))
                if qf:
                    self.stats.bump("quorum_fail_rounds", qf)
                out[gid] = commit
        return out

    def commit_groups(self, work: list) -> Optional[dict]:
        """Synchronous dispatch: stage, run, and adopt ONE group-major
        window (the pre-async contract; tests and single-shot callers).
        Returns {gid: device_commit} for the non-stale items, or None
        when nothing was dispatchable."""
        win = self.dispatch_groups(work)
        if win is None:
            return None
        return self.adopt_window(win)

    # -- follower shard readback ------------------------------------------

    def shard_end(self, gid: int, replica: int,
                  gen: int) -> Optional[int]:
        from apus_tpu.ops.logplane import OFF_END
        if not (0 <= replica < self.n_replicas):
            return None
        with self.lock:
            if gen != self.generations[gid]:
                return None
            row = self._offs_one(self._devlog.offs, np.int32(gid),
                                 np.int32(replica))
        return int(np.asarray(row)[OFF_END])

    def read_rows(self, gid: int, replica: int, gen: int, lo: int,
                  hi: int, window: bool = False):
        from apus_tpu.core.log import LogEntry  # noqa: F401 (decode)
        from apus_tpu.ops.logplane import META_IDX, META_LEN, slot_of
        if not (0 <= replica < self.n_replicas):
            return None
        cap = self.batch * (self.max_depth if window else 1)
        hi = min(hi, lo + cap)
        n = self.batch if hi - lo <= self.batch else cap
        slots = slot_of(lo + np.arange(n, dtype=np.int64),
                        self.n_slots).astype(np.int32)
        with self.lock:
            if gen != self.generations[gid]:
                return None
            if hi <= lo:
                return []
            data_rows, meta_rows = self._gather(
                self._devlog.data, self._devlog.meta, np.int32(gid),
                np.int32(replica), slots)
        data = np.asarray(data_rows)
        meta = np.asarray(meta_rows)
        out = []
        for j, idx in enumerate(range(lo, hi)):
            if int(meta[j, META_IDX]) != idx:
                break
            blob = data[j, :int(meta[j, META_LEN])].tobytes()
            try:
                e = wire.decode_entry(wire.Reader(blob))
            except Exception:
                break
            if e.idx != idx:
                break
            out.append(e)
        return out


class _InFlightWindow:
    """Handle for one dispatched-but-not-yet-adopted group-major
    window: the device arrays carrying its per-round commits, the
    work items it carried, and the per-group generations at dispatch
    (adoption drops groups whose generation moved)."""

    __slots__ = ("items", "commits", "gens")

    def __init__(self, items, commits, gens):
        self.items = items      # [(gid, ldr, trm, end0, cid, live, n)]
        self.commits = commits  # device array [MD, G]
        self.gens = gens        # {gid: generation at dispatch}


class _GState:
    """Per-group driver-side cursor state."""

    __slots__ = ("gen", "base", "next", "last_adv", "qfail_since",
                 "qfail_pause_until", "cooldown_until", "gate_since",
                 "last_end_seen", "drain_idle_key")

    def __init__(self):
        self.gen = None
        self.base = 0
        self.next = 0
        self.last_adv = 0.0
        self.qfail_since = None
        self.qfail_pause_until = 0.0
        self.cooldown_until = 0.0
        self.gate_since = None
        self.last_end_seen = 0
        self.drain_idle_key = None


class GroupPlaneDriver:
    """One thread per daemon driving ALL of its groups through the
    shared group-major runner."""

    def __init__(self, daemon, runner: GroupDeviceRunner):
        self.daemon = daemon
        self.runner = runner
        self.logger = daemon.logger
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._g = {gid: _GState()
                   for gid in range(runner.n_groups)}
        #: the one dispatched-but-unadopted window of the async beat
        #: ((_InFlightWindow, terms) or None) — owned by the driver
        #: thread only.
        self._inflight = None
        self.stats = {"rounds": 0, "drained": 0, "holes": 0,
                      "fallbacks": 0, "partial_deferrals": 0,
                      "group_windows": 0}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self.daemon.lock:
            for gid in range(self.runner.n_groups):
                node = self.daemon.group_node(gid)
                if node is not None:
                    node.pre_election_hook = \
                        self._make_election_hook(gid)
            self.daemon.on_tick.append(self._tick_watchdog)
        t = threading.Thread(target=self._run,
                             name=f"apus-groupplane-{self.daemon.idx}",
                             daemon=True)
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        with self.daemon.lock:
            for gid in range(self.runner.n_groups):
                node = self.daemon.group_node(gid)
                if node is not None:
                    self._set_owned(node, False, "driver_stop")
                    node.pre_election_hook = None
            if self._tick_watchdog in self.daemon.on_tick:
                self.daemon.on_tick.remove(self._tick_watchdog)

    def _set_owned(self, node, owned: bool, cause: str) -> None:
        if bool(node.external_commit) == owned:
            return
        node.external_commit = owned
        node.bump("devplane_own_flips")
        node._note("devplane", "own" if owned else "release",
                   cause=cause, gid=node.gid, commit=node.log.commit)

    def _tick_watchdog(self) -> None:
        """Under the daemon lock, tick thread: per group, release
        device commit ownership when it stalls (the driver thread may
        itself be wedged in a dispatch)."""
        window = max(4 * self.daemon.spec.hb_timeout, 0.5)
        md_ms = self.runner.stats.get("max_dispatch_ms")
        if md_ms:
            window = max(window, 2.5 * md_ms / 1e3)
        now = time.monotonic()
        for gid, st in self._g.items():
            node = self.daemon.group_node(gid)
            if node is None or not (node.is_leader
                                    and node.external_commit):
                continue
            if node.log.end > node.log.commit \
                    and now - st.last_adv > window:
                self._set_owned(node, False, "stall_watchdog")
                st.cooldown_until = now + window
                self.stats["fallbacks"] += 1
                node._note("watchdog", "devplane_stall_fallback",
                           gid=gid, window_s=round(window, 3))

    # -- main loop ---------------------------------------------------------

    def _run(self) -> None:
        poll = max(self.daemon._tick_interval, 0.0005)
        while not self._stop.is_set():
            try:
                if not self._step_once():
                    time.sleep(poll)
            except Exception:
                self.logger.exception("group-plane driver error")
                self._inflight = None
                with self.daemon.lock:
                    for gid in self._g:
                        node = self.daemon.group_node(gid)
                        if node is not None:
                            self._set_owned(node, False, "driver_error")
                        self._g[gid].gen = None
                time.sleep(10 * poll)

    def _step_once(self) -> bool:
        work = []
        terms = {}
        led = 0
        with self.daemon.lock:
            for gid, st in self._g.items():
                node = self.daemon.group_node(gid)
                if node is None:
                    continue
                if node.is_leader:
                    if st.gen is not None:
                        led += 1
                    item = self._collect_leader(gid, st, node)
                    if item is not None:
                        work.append(item)
                        terms[gid] = node.current_term
                elif st.gen is not None:
                    st.gen = None
                    self._set_owned(node, False, "role_change")
        if work and len(work) < led:
            # Group-commit accumulation beat: this daemon leads MORE
            # groups than have a window ready — one tick of patience
            # often lets their queued admissions land, so the dispatch
            # below carries them too (the group-major amortization this
            # plane exists for) instead of paying one dispatch each.
            time.sleep(2 * self.daemon._tick_interval)
            with self.daemon.lock:
                have = {w[0] for w in work}
                for gid, st in self._g.items():
                    if gid in have:
                        continue
                    node = self.daemon.group_node(gid)
                    if node is not None and node.is_leader:
                        item = self._collect_leader(gid, st, node)
                        if item is not None:
                            work.append(item)
                            terms[gid] = node.current_term
        # The ASYNC BEAT: dispatch window N+1 (host staging + enqueue,
        # no device wait) BEFORE fencing window N, so N's device
        # execution overlapped this pass's collection AND N+1's
        # staging; then adopt N's commits at the one fence.  With no
        # new work the in-flight window is adopted immediately, so a
        # lone window's commit latency is one fence, not one beat.
        prev = self._inflight
        self._inflight = None
        did = False
        if work:
            # (the runner's _open_windows tracking bumps
            # dev_async_overlap_windows when this dispatch's staging
            # overlapped prev's execution)
            self._inflight = self._dispatch_async(work, terms)
            did = True
        if prev is not None:
            self._adopt_inflight(prev)
            did = True
        # Follower drains (outside the daemon lock for the gathers).
        for gid in self._g:
            if self._follower_drain(gid):
                did = True
        return did

    def _live_members(self, node) -> set:
        window = max(node._hb_timeout,
                     4 * self.daemon.spec.hb_period, 0.25)
        now = time.monotonic()
        live = {node.idx}
        touched = node.regions.touched
        for m in node.cid.members():
            if m == node.idx:
                continue
            t = touched.get((Region.REP_ACK, m))
            if t is not None and now - t <= window:
                live.add(m)
        return live

    def _live_covers_quorum(self, cid, live) -> bool:
        old = sum(1 for m in live if cid.contains(m) and m < cid.size)
        if old < quorum_size(cid.size):
            return False
        if cid.state == CidState.TRANSIT:
            new = sum(1 for m in live
                      if cid.contains(m) and m < cid.new_size)
            if new < quorum_size(cid.new_size):
                return False
        return True

    def _collect_leader(self, gid: int, st: _GState, node):
        """Under the daemon lock: one group's dispatchable window (or
        None).  Mirrors the single-group driver's gating, simplified to
        the sync group-major dispatch shape."""
        B, MD = self.runner.batch, self.runner.max_depth
        term = node.current_term
        if not self.runner.quorum_coverable(node.cid):
            if st.gen is not None:
                st.gen = None
                self._set_owned(node, False, "coverage_lost")
                node.device_covered_from = None
                self.stats["fallbacks"] += 1
            return None
        if st.gen is None or self.runner._term[gid] != term \
                or self.runner._leader[gid] != node.idx:
            self._reset_group_leadership(gid, st, node, term)
            return None
        if st.next < node.log.head:
            st.gen = None               # pruned past the cursor: re-base
            return None
        now = time.monotonic()
        # Re-arm ownership once host commit covered the device base and
        # the cursor caught up (same rules as the single-group driver).
        if not node.external_commit and node.log.commit >= st.base \
                and now >= st.cooldown_until \
                and st.next >= node.log.commit:
            self._set_owned(node, True, "cursor_catchup")
            st.last_adv = now + max(4 * self.daemon.spec.hb_timeout, 0.5)
        live = self._live_members(node)
        if not self._live_covers_quorum(node.cid, live):
            window = max(4 * self.daemon.spec.hb_timeout, 0.5)
            if st.gate_since is None:
                st.gate_since = now
            elif now - st.gate_since > window and node.external_commit:
                self._set_owned(node, False, "quorum_gate")
                st.cooldown_until = now + window
                self.stats["fallbacks"] += 1
            return None
        st.gate_since = None
        if now < st.qfail_pause_until:
            return None
        end = node.log.end
        if end <= st.next:
            return None
        # Micro-batching: defer a partial batch while arrivals are
        # still landing or admissions are queued (see the single-group
        # driver's occupancy rationale); pad with NOOPs once they
        # pause.
        if end - st.next < B and (
                end != st.last_end_seen
                or (not node.log.near_full(3)
                    and any(p.idx is None for p in node._pending))):
            self.stats["partial_deferrals"] += 1
            st.last_end_seen = end
            return None
        st.last_end_seen = end
        if end - st.next < B:
            while (node.log.end - st.next) % B != 0 \
                    and not node.log.near_full(2):
                node.log.append(term, type=EntryType.NOOP)
            if (node.log.end - st.next) % B != 0:
                return None
            end = node.log.end
        n = min((end - st.next) // B, MD)
        span = list(node.log.entries(st.next, st.next + n * B))
        while n > 0:
            span_n = span[:n * B]
            if len(span_n) == n * B and not any(
                    wire.entry_wire_size(e) > self.runner.slot_bytes
                    for e in span_n):
                break
            n -= 1
        if n <= 0:
            # Oversized entry leads the span: that window is the host
            # path's; re-base past it once host commit covers it.
            self.stats["holes"] += 1
            self._set_owned(node, False, "oversize_hole")
            if node.log.commit >= st.next + B:
                st.gen = None
            return None
        return (gid, st.gen, st.next, span[:n * B], node.cid, live)

    def _reset_group_leadership(self, gid: int, st: _GState, node,
                                term: int) -> None:
        B = self.runner.batch
        while (node.log.end - 1) % B != 0 and not node.log.near_full(2):
            node.log.append(term, type=EntryType.NOOP)
        if (node.log.end - 1) % B != 0:
            return
        base = node.log.end
        idx = node.idx
        self.daemon.lock.release()
        try:
            gen = self.runner.reset_group(gid, idx, term, base)
        finally:
            self.daemon.lock.acquire()
        if gen is None or self._stop.is_set() \
                or not (node.is_leader and node.current_term == term):
            return
        st.gen = gen
        st.base = base
        st.next = base
        st.last_end_seen = 0
        st.last_adv = time.monotonic() + \
            max(4 * self.daemon.spec.hb_timeout, 0.5)
        self._set_owned(node, node.log.commit >= base,
                        "leadership_reset")
        node.device_covered_from = base

    def _dispatch_async(self, work: list, terms: dict):
        """Stage + enqueue the group-major window OUTSIDE the daemon
        lock, then advance the driver cursors for whatever the runner
        accepted — the chaining edge that lets the next collection
        pass build window N+1 while N executes.  Returns the in-flight
        (window, terms) pair for ``_adopt_inflight``, or None."""
        win = self.runner.dispatch_groups(work)
        self.stats["dispatches"] = self.stats.get("dispatches", 0) + 1
        with self.daemon.lock:
            self._check_recompiles()
            dispatched = set() if win is None \
                else {it[0] for it in win.items}
            for gid, gen, end0, entries, _cid, _live in work:
                st = self._g[gid]
                if gid not in dispatched:
                    st.gen = None       # stale: re-base next pass
                    continue
                n = len(entries) // self.runner.batch
                st.next = end0 + n * self.runner.batch
                self.stats["rounds"] += n
                self.stats["group_windows"] += 1
        if win is None:
            return None
        return (win, terms)

    def _adopt_inflight(self, inflight) -> None:
        """The adoption fence: wait for the window's device commits
        (the ONE blocking point of the beat), then adopt each group's
        result under the daemon lock with the per-group safety rules
        (commit chaining, flr cap, term pin) unchanged."""
        win, terms = inflight
        res = self.runner.adopt_window(win)
        with self.daemon.lock:
            for gid, _l, _t, end0, _c, _lv, n in win.items:
                st = self._g[gid]
                node = self.daemon.group_node(gid)
                if gid not in res:
                    st.gen = None       # reset mid-flight: re-base
                    continue
                if node is None or self._stop.is_set() \
                        or not (node.is_leader
                                and node.current_term == terms[gid]):
                    st.gen = None
                    continue
                self._adopt_commit(gid, st, node, res[gid])
                self._note_quorum(gid, st, node, res[gid] > end0)

    def _check_recompiles(self) -> None:
        for name, old, new in self.runner.check_recompiles():
            self.daemon.node._note("devplane", "recompile", exe=name,
                                   cached_before=old, cached_after=new)
            self.logger.warning(
                "group plane: post-warmup XLA recompile (%r)", name)

    def _adopt_commit(self, gid: int, st: _GState, node,
                      dev_commit: int) -> None:
        cap = node.flr_commit_cap()
        if cap is not None:
            dev_commit = min(dev_commit, cap)
        if node.log.commit >= st.base and dev_commit > node.log.commit:
            before = node.log.commit
            after = node.log.advance_commit(min(dev_commit,
                                                node.log.end))
            if after > before:
                st.last_adv = time.monotonic()
                node.bump("commits")
                node.bump("devplane_commits")
                self.daemon.commit_cond.notify_all()

    def _note_quorum(self, gid: int, st: _GState, node,
                     advanced: bool) -> None:
        if advanced:
            st.qfail_since = None
            return
        now = time.monotonic()
        if st.qfail_since is None:
            st.qfail_since = now
            return
        window = max(4 * self.daemon.spec.hb_timeout, 0.5)
        if now - st.qfail_since > window:
            st.qfail_since = None
            st.qfail_pause_until = now + window
            if node.external_commit:
                self._set_owned(node, False, "quorum_fail_streak")
                self.stats["fallbacks"] += 1
            st.cooldown_until = max(st.cooldown_until, now + window)
            st.gen = None               # cursor diverged: re-base
            self.stats["qfail_timeouts"] = \
                self.stats.get("qfail_timeouts", 0) + 1

    # -- follower drain + election reconciliation --------------------------

    def _follower_drain(self, gid: int) -> bool:
        node = self.daemon.group_node(gid)
        st = self._g[gid]
        if node is None \
                or not self.runner.covers_replica(self.daemon.idx):
            return False
        gen = self.runner.generations[gid]
        if gen == 0:
            return False
        key = (gen, self.runner.stats["rounds"])
        if key == st.drain_idle_key:
            return False
        with self.daemon.lock:
            if node.is_leader:
                return False
            term = node.current_term
            end = node.log.end
            prev = node.log.get(end - 1)
            if prev is None or prev.term != term:
                return False
        shard_end = self.runner.shard_end(gid, self.daemon.idx, gen)
        if shard_end is None or shard_end <= end:
            st.drain_idle_key = key
            return False
        rows = self.runner.read_rows(
            gid, self.daemon.idx, gen, end,
            min(shard_end,
                end + self.runner.max_depth * self.runner.batch),
            window=shard_end - end > self.runner.batch)
        if not rows:
            st.drain_idle_key = key
            return False
        appended = 0
        with self.daemon.lock:
            if node.is_leader or node.current_term != term:
                return False
            for e in rows:
                if e.term != term or e.idx != node.log.end \
                        or node.log.near_full(1):
                    break
                node.log.write(e)
                appended += 1
        self.stats["drained"] += appended
        return appended > 0

    def _make_election_hook(self, gid: int):
        """pre_election_hook closure: absorb this group's shard into
        the host log before this replica votes or campaigns in that
        group (the device quorum attests SHARD placement)."""

        def hook():
            node = self.daemon.group_node(gid)
            if node is None \
                    or not self.runner.covers_replica(self.daemon.idx):
                return
            while True:
                gen = self.runner.generations[gid]
                if gen == 0:
                    return
                term = node.current_term
                end = node.log.end
                prev = node.log.get(end - 1)
                if prev is None or prev.term != term:
                    return
                shard_end = self.runner.shard_end(gid, self.daemon.idx,
                                                  gen)
                if shard_end is None or shard_end <= end:
                    return
                rows = self.runner.read_rows(
                    gid, self.daemon.idx, gen, end,
                    min(shard_end, end + self.runner.max_depth
                        * self.runner.batch),
                    window=shard_end - end > self.runner.batch)
                if not rows:
                    return
                appended = 0
                for e in rows:
                    if e.term != term or e.idx != node.log.end \
                            or node.log.near_full(1):
                        break
                    node.log.write(e)
                    appended += 1
                self.stats["drained"] += appended
                if appended == 0:
                    return

        return hook
