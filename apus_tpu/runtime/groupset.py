"""GroupSet: N independent consensus groups multiplexed over one daemon.

The Multi-Raft substrate (ROADMAP "Multi-group sharded consensus"):
the keyspace is sharded into ``spec.groups`` independent consensus
groups — per-group ``Node`` state (log, state machine, endpoint DB,
cid/config epochs, leases, incarnation) — multiplexed over the SAME
daemon set, listen sockets, transport connections, fault plane, clock
seam, and (when enabled) device plane.  DXRAM-style range partitioning
reaches scale exactly this way: many small replication groups per node,
one infrastructure set (PAPERS.md).

Shared vs per-group state:

    shared (one per daemon)            per group (one per gid)
    -------------------------------    --------------------------------
    PeerServer ingest loop + socket    Node (log, sm, epdb, cid, sid)
    NetTransport connections/backoff   GroupTransport view (OP_GROUP)
    FaultPlane (one schedule)          leases (leader + follower)
    SkewClock (one time domain)        incarnation / fence tables
    failure-evidence (dial/timeout)    election timers (same envelope,
    tick thread + node lock              per-group rng phase)
    heartbeat COALESCER (OP_HB_MULTI)  REP_ACK / vote / HB regions
    obs hub (counters aggregate;       pending client requests/reads
      per-group gauges at scrape)      snapshots / catch-up state

Wire: group 0 frames are never wrapped (``groups == 1`` stays
byte-identical to the single-group protocol); groups 1..N-1 ride
``wire.OP_GROUP | gid | <inner frame>`` through the same sockets, and
the PeerServer demuxes on gid (``PeerServer.group_ref``).

Heartbeat coalescing: each leader-role node registers its HB round with
the daemon-level coalescer (``Node.hb_sink``) instead of fanning out
per-group ctrl writes; after the tick pass the GroupSet flushes ONE
``OP_HB_MULTI`` frame per peer carrying every registered group's
(term, commit, lease, incarnation) vector, and distributes the
per-group reply echoes back into each node's lease-renewal accounting
(``Node.hb_round_finish``) — N groups' failure detection and lease
renewal ride one frame per peer per period.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from apus_tpu.core.cid import Cid
from apus_tpu.core.node import Node
from apus_tpu.models.kvs import KvsStateMachine
from apus_tpu.parallel import wire
from apus_tpu.parallel.net import GroupTransport


class GroupPort:
    """What ``PeerServer.group_ref(gid)`` returns: the group's node for
    one-sided ops plus its two-sided handler table (client, membership,
    flr ops bound to that node)."""

    __slots__ = ("node", "extra_ops")

    def __init__(self, node: Node, extra_ops: dict):
        self.node = node
        self.extra_ops = extra_ops


class GroupSet:
    """All extra consensus groups (gid 1..n-1) of one daemon.  Group 0
    stays the daemon's primary ``daemon.node`` — membership service
    discovery, persistence, and the app bridge keep riding it — but is
    also reachable through port(0) so uniformly group-wrapped clients
    work."""

    def __init__(self, daemon, n_groups: int,
                 cids: Optional[dict] = None,
                 sm_factory=KvsStateMachine):
        assert n_groups >= 2, n_groups
        self.daemon = daemon
        self.n_groups = n_groups
        self.sm_factory = sm_factory
        self.nodes: list[Node] = [daemon.node]
        self._ports: dict[int, GroupPort] = {}
        self._hb_items: list[tuple] = []      # (node, my_sid, t0)
        self._wake: tuple = ()
        self._last_roles: dict[int, tuple] = {}
        # Per-group durable stores (elastic-group plane): gid ->
        # Persistence, with the daemon's disk-fault containment policy
        # applied PER GROUP (one group's dead disk path never disables
        # a sibling's).  Attached by the daemon when it has a db_dir.
        self.db_dir: Optional[str] = None
        self.persists: dict = {}
        self.persist_disabled: dict[int, bool] = {}
        self.persist_errors: dict[int, int] = {}
        cids = cids or {}
        self._build_port(0)
        for gid in range(1, n_groups):
            self._make_group(gid, cids.get(gid),
                             adopt_incarnation=cids.get(gid)
                             is not None)
        # Group 0 heartbeats coalesce into the same per-peer frames.
        daemon.node.hb_sink = self.hb_sink

    def _make_group(self, gid: int, cid: Optional[Cid],
                    adopt_incarnation: bool = False) -> Node:
        daemon = self.daemon
        cfg0 = daemon._node_cfg
        # Per-group election phase: same timing envelope, distinct
        # rng stream per (daemon, gid) so different groups tend to
        # elect leaders on different daemons (the load-spreading
        # the sharding exists for), while the ENVELOPE — and the
        # clock seam every timer reads — stays shared.
        cfg = dataclasses.replace(cfg0, seed=cfg0.seed + 7919 * gid)
        gt = GroupTransport(daemon.transport, gid)
        if cid is None:
            cid = Cid.initial(daemon.spec.group_size)
        node = Node(cfg, cid, self.sm_factory(), gt)
        node.gid = gid
        node.clock = daemon.clock
        node.async_snap_push = True
        if adopt_incarnation:
            node.incarnation = cid.epoch
        gt.incarnation_of = (lambda n=node: n.incarnation)
        if daemon.obs is not None:
            node.attach_obs(daemon.obs)
        # Same cold-start election grace as the primary node.
        node._last_hb_seen = (daemon.clock()
                              + node.rng.random()
                              * node.cfg.elect_high)
        node.hb_sink = self.hb_sink
        self._install_flr(node, gt)
        assert gid == len(self.nodes), (gid, len(self.nodes))
        self.nodes.append(node)
        self._build_port(gid)
        return node

    def ensure_group(self, gid: int, cid: Optional[Cid]) -> Node:
        """Create consensus group ``gid`` ONLINE (the elastic SPLIT
        path / a daemon learning a group it missed).  Sequential gids
        only; idempotent for existing ones.  Caller holds the daemon
        lock; the new group's store attaches immediately (empty — it
        was just born) when this daemon persists."""
        if gid < len(self.nodes):
            return self.nodes[gid]
        node = self._make_group(gid, cid)
        self.n_groups = len(self.nodes)
        self.daemon.n_groups = self.n_groups
        if self.db_dir is not None:
            self._attach_store(gid)
        self.daemon.logger.info("group %d created online (%r)", gid,
                                node.cid)
        return node

    # -- per-group durable stores (elastic-group durability) ---------------

    def attach_persistence(self, db_dir: str) -> None:
        """Give every EXTRA group its own durable store under the
        replica's db dir (``apus_records.<idx>.g<gid>.db``) and replay
        it: each group's SM/epdb rebuild independently and its log
        RE-BASES at its own replay point — a whole-group quorum
        SIGKILL + restart now recovers every acked write of every
        group from disk, exactly like group 0 (the ROADMAP's "extra
        groups carry NO durable store" hole).  Called once at daemon
        construction, before serving.  Store files beyond the static
        group count re-create their groups first (a split survives a
        full-cluster restart)."""
        import re

        self.db_dir = db_dir
        pat = re.compile(
            rf"apus_records\.{self.daemon.idx}\.g(\d+)\.db$")
        found = []
        try:
            for name in os.listdir(db_dir):
                m = pat.match(name)
                if m:
                    found.append(int(m.group(1)))
        except OSError:
            pass
        # Static groups replay FIRST: split-born groups' genesis cids
        # are recovered from the MB records in their (replayed) src
        # groups' SMs below.
        for gid in range(1, self.n_groups):
            self._attach_store(gid)
        # Dynamic groups born by splits: their store files are the
        # durable evidence they existed — re-create them (ascending,
        # so a second-generation split's src is replayed before its
        # dst) with the REPLICATED genesis cid where the replayed MB
        # record carries it; ensure_group replays each store.
        for gid in sorted(found):
            while gid >= self.n_groups:
                self.ensure_group(self.n_groups,
                                  self._genesis_cid(self.n_groups))

    def _genesis_cid(self, gid: int) -> Optional[Cid]:
        """Genesis cid of a split-born group from the MB record in any
        replayed local SM (None -> Cid.initial fallback)."""
        from apus_tpu.core.cid import CidState
        for n in self.nodes:
            for rec in getattr(n.sm, "migs_out", {}).values():
                if rec[0] == gid and len(rec) > 5 and rec[4]:
                    return Cid(epoch=0, state=CidState.STABLE,
                               size=rec[4], new_size=0,
                               bitmask=rec[5])
        return None

    def _attach_store(self, gid: int) -> None:
        from apus_tpu.runtime.persist import (Persistence,
                                              daemon_store_path)
        if gid in self.persists:
            return
        daemon = self.daemon
        node = self.nodes[gid]
        # Per-group snapshot spool subdir: inbound stream partials of
        # different groups must never collide on the deterministic
        # per-slot file name.
        spool = os.path.join(self.db_dir, f"g{gid}")
        try:
            os.makedirs(spool, exist_ok=True)
            node.snap_spool_dir = spool
        except OSError:
            pass
        p = Persistence(
            daemon_store_path(self.db_dir, daemon.idx, gid=gid),
            sync_policy=getattr(daemon.spec, "sync_policy", "batch"),
            logger=daemon.logger)
        self.persists[gid] = p
        self.persist_disabled[gid] = False
        self.persist_errors[gid] = 0
        if p.store.count:
            p.replay_into(node.sm, node.epdb, node=node)
            daemon.logger.info(
                "group %d store replayed: apply floor %d "
                "(re-based)", gid, node.log.apply)

    def _persist_fail(self, gid: int, stage: str, exc: OSError) -> None:
        """Group-scoped arm of the daemon's first-error-disables
        policy (daemon._persist_fail rationale)."""
        self.persist_errors[gid] = self.persist_errors.get(gid, 0) + 1
        if self.persist_disabled.get(gid):
            return
        self.persist_disabled[gid] = True
        if self.daemon.obs is not None:
            self.daemon.obs.flight.note("persist", "disabled",
                                        gid=gid, stage=stage,
                                        error=repr(exc))
        self.daemon.logger.error(
            "group %d PERSISTENCE DISABLED for this session: %s "
            "failed (%s); the group keeps serving — durability of "
            "acked writes remains replication", gid, stage, exc)

    # -- ports (PeerServer demux) -----------------------------------------

    def _build_port(self, gid: int) -> None:
        from apus_tpu.runtime.client import make_client_ops
        from apus_tpu.runtime.flr import make_flr_ops
        from apus_tpu.runtime.membership import make_membership_ops
        node = self.nodes[gid]
        ops = {**make_client_ops(self.daemon, node=node),
               **make_membership_ops(self.daemon, node=node),
               **make_flr_ops(self.daemon, node=node)}
        self._ports[gid] = GroupPort(node, ops)

    def port(self, gid: int) -> Optional[GroupPort]:
        return self._ports.get(gid)

    def node(self, gid: int) -> Optional[Node]:
        return self.nodes[gid] if 0 <= gid < len(self.nodes) else None

    # -- tick integration (runs under the daemon lock) ---------------------

    def tick(self, now: float) -> None:
        """Tick every EXTRA group (the daemon ticks group 0 itself),
        drain their upcalls, and record role edges.  Called under the
        daemon lock from the tick thread, after group 0's tick."""
        for node in self.nodes[1:]:
            node.tick(now)
            self._drain_group_upcalls(node)
            self._log_role(node)
        # Batch sync policy, per group: one fdatasync per drain window
        # per group that appended (exactly daemon._persist_flush).
        for gid, p in self.persists.items():
            if self.persist_disabled.get(gid):
                continue
            try:
                p.flush_window()
            except OSError as exc:
                self._persist_fail(gid, "fsync", exc)

    def wake_state(self) -> tuple:
        """Extra groups' contribution to the daemon's waiter-predicate
        wake tuple (apply/commit/end/role/term/reads per group)."""
        return tuple((n.log.apply, n.log.commit, n.log.end, n.role,
                      n.current_term, n.reads_done)
                     for n in self.nodes[1:])

    def begin_drain(self) -> None:
        """Graceful leave: stop every group's voting/acking (the daemon
        flips group 0 itself)."""
        for node in self.nodes[1:]:
            node.draining = True

    def _log_role(self, node: Node) -> None:
        role = (node.role, node.current_term)
        if role != self._last_roles.get(node.gid):
            self._last_roles[node.gid] = role
            if self.daemon.obs is not None:
                self.daemon.obs.flight.note(
                    "role", node.role.name, gid=node.gid,
                    term=node.current_term, commit=node.log.commit)
            self.daemon.logger.info("[g%d T%d] %s", node.gid,
                                    node.current_term, node.role.name)

    def _drain_group_upcalls(self, node: Node) -> None:
        # Per-group durability: committed entries and installed
        # snapshots land in THIS group's store (group 0's drain is
        # daemon._drain_upcalls); extra groups still carry no app
        # bridge.  Elastic migration records (M*) additionally mark
        # the daemon's derived shard map dirty.
        gid = node.gid
        p = self.persists.get(gid)
        disabled = self.persist_disabled.get(gid, False)
        if node.snapshot_upcalls:
            snaps, node.snapshot_upcalls = node.snapshot_upcalls, []
            if self.daemon.elastic is not None:
                # A snapshot install may have replaced SM migration
                # state wholesale.
                self.daemon.elastic.dirty = True
            if p is not None and not disabled:
                for snap, ep_dump in snaps:
                    # Stale file-backed captures are skipped exactly as
                    # in daemon._drain_upcalls (generation fence).
                    if snap.data_path is not None and snap.data_gen \
                            != getattr(node.sm, "dump_generation", 0):
                        continue
                    try:
                        p.on_snapshot(snap, ep_dump)
                    except OSError as exc:
                        self._persist_fail(gid, "snapshot record", exc)
                        break
        if node.committed_upcalls:
            entries, node.committed_upcalls = \
                node.committed_upcalls, []
            if self.daemon.elastic is not None:
                for e in entries:
                    if e.data[:1] != b"M":
                        continue
                    self.daemon.elastic.dirty = True
                    if e.data[:2] == b"MB":
                        # Split freeze applied: create the dst group
                        # from the record's replicated genesis cid.
                        self.daemon.elastic.ensure_from_begin(e.data)
            if p is not None and not self.persist_disabled.get(gid):
                for e in entries:
                    try:
                        p.on_commit(e)
                    except OSError as exc:
                        self._persist_fail(gid, "entry append", exc)
                        break
        if node.config_upcalls:
            cfgs, node.config_upcalls = node.config_upcalls, []
            for e in cfgs:
                self._group_config(node, e)

    def _group_config(self, node: Node, e) -> None:
        """Applied CONFIG entry in an extra group: learn peer addresses
        into the SHARED peer table/transport.  Guarded on address
        change — group 0 applies the same join and owns the full
        set_peer (connection + established-state reset); re-running it
        per group would drop the shared connection N times."""
        if not e.data or e.data.startswith(b"leave "):
            return
        try:
            slot_s, addr = e.data.decode().split(" ", 1)
            slot = int(slot_s)
        except ValueError:
            return
        peers = self.daemon.spec.peers
        known = peers[slot] if slot < len(peers) else ""
        if addr == known:
            return
        if slot != self.daemon.idx:
            host, port_s = addr.rsplit(":", 1)
            self.daemon.transport.set_peer(slot, (host, int(port_s)))
        while len(peers) <= slot:
            peers.append("")
        peers[slot] = addr

    # -- follower read leases (per group) ----------------------------------

    def _install_flr(self, node: Node, gt: GroupTransport) -> None:
        from apus_tpu.runtime.flr import _parse_grant, _request_payload
        daemon = self.daemon

        def request(leader_idx: int, want=None, node=node, gt=gt):
            payload = _request_payload(daemon.idx, node.incarnation,
                                       want)
            return _parse_grant(gt.request(leader_idx, payload))

        node.lease_requester = request

    # -- coalesced heartbeats ----------------------------------------------

    def hb_sink(self, node: Node, my, t0: float) -> None:
        """Node._send_heartbeats registration point (under the daemon
        lock, inside that node's tick)."""
        self._hb_items.append((node, my, t0))

    def flush_heartbeats(self) -> None:
        """One OP_HB_MULTI frame per peer carrying every group
        registered this tick pass; per-group results distributed back
        into Node.hb_round_finish.  Called under the daemon lock after
        ALL groups ticked; the transport yields the lock on the wire
        (hb_round_finish re-validates leadership before renewing)."""
        items, self._hb_items = self._hb_items, []
        if not items:
            return
        daemon = self.daemon
        fresh = daemon.clock()
        # peer -> [(item_pos_in_frame, node, my, t0)]
        per_peer: dict[int, list] = {}
        frames: dict[int, list] = {}
        for node, my, t0 in items:
            lease_us = max(0, min(0xFFFFFFFF,
                                  int((node._lease_until - fresh) * 1e6)))
            for peer in node._replication_targets():
                lst = frames.setdefault(peer, [])
                per_peer.setdefault(peer, []).append(
                    (len(lst), node, my, t0))
                lst.append((node.gid, my.word, node.log.commit,
                            lease_us, node.incarnation))
        daemon.node.bump("hb_coalesced_groups", len(items))
        # node -> {peer: (status, echo)}
        results: dict[int, dict] = {id(n): {} for n, _m, _t in items}
        for peer, lst in frames.items():
            payload = wire.encode_hb_multi(daemon.idx, lst)
            resp = daemon.transport.request(peer, payload)
            echoes = (wire.decode_hb_echoes(resp, len(lst))
                      if resp is not None else None)
            for pos, node, my, t0 in per_peer[peer]:
                if echoes is None:
                    results[id(node)][peer] = ("fail", None)
                    continue
                st, word = echoes[pos]
                if st == wire.ST_FENCED:
                    results[id(node)][peer] = ("fenced", None)
                elif st == wire.ST_OK:
                    results[id(node)][peer] = ("ok", word)
                else:
                    results[id(node)][peer] = ("fail", None)
        for node, my, t0 in items:
            node.hb_round_finish(my, t0, results[id(node)])

    # -- observability ------------------------------------------------------

    def status_view(self) -> dict:
        """The OP_STATUS ``groups`` view: per-group role/term/offsets/
        config — callers assert per-group convergence over the wire
        instead of log-scraping.  Under the daemon lock."""
        out = {}
        elastic = self.daemon.elastic
        shard = elastic.shard_map() if elastic is not None else None
        for gid, n in enumerate(self.nodes):
            gv = {
                "role": n.role.name,
                "is_leader": n.is_leader,
                "term": n.current_term,
                "leader_hint": n.leader_hint,
                "commit": n.log.commit,
                "apply": n.log.apply,
                "end": n.log.end,
                "epoch": n.cid.epoch,
                "cid_state": n.cid.state.name,
                "members": [i for i in range(n.cid.extended_group_size)
                            if n.cid.contains(i)],
            }
            # Per-group durability view (elastic-group plane): group
            # 0's numbers come from the daemon's own store.
            if gid == 0:
                p = getattr(self.daemon, "persistence", None)
                dis = getattr(self.daemon, "persist_disabled", False)
                errs = getattr(self.daemon, "persist_errors", 0)
            else:
                p = self.persists.get(gid)
                dis = self.persist_disabled.get(gid, False)
                errs = self.persist_errors.get(gid, 0)
            if p is not None:
                gv["persist_floor"] = p.compaction_floor
                gv["records_since_base"] = p.entries_since_base
                gv["compactions"] = p.compactions
                gv["persist_disabled"] = dis
                gv["persist_errors"] = errs
            if shard is not None:
                gv["owned_buckets"] = sum(
                    1 for g in shard.assign if g == gid)
                gv["frozen_buckets"] = len(
                    getattr(n.sm, "_frozen", ()) or ())
            out[str(gid)] = gv
        return out

    def scrape_gauges(self, registry) -> None:
        """Per-group dimension for the OP_METRICS scrape: a small fixed
        set of per-group namespaced gauges (``nodeg<gid>_*``), mirrored
        at scrape time like the daemon_* gauges."""
        for gid, n in enumerate(self.nodes):
            p = f"nodeg{gid}"
            registry.gauge(f"{p}_term").set(n.current_term)
            registry.gauge(f"{p}_commit").set(n.log.commit)
            registry.gauge(f"{p}_apply").set(n.log.apply)
            registry.gauge(f"{p}_end").set(n.log.end)
            registry.gauge(f"{p}_is_leader").set(1 if n.is_leader else 0)
            registry.gauge(f"{p}_epoch").set(n.cid.epoch)
            # Per-group durability gauges (elastic-group plane).
            store = (getattr(self.daemon, "persistence", None)
                     if gid == 0 else self.persists.get(gid))
            if store is not None:
                registry.gauge(f"{p}_persist_floor").set(
                    store.compaction_floor)
                registry.gauge(f"{p}_persist_records_since_base").set(
                    store.entries_since_base)
                registry.gauge(f"{p}_persist_compactions").set(
                    store.compactions)
