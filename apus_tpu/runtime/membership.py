"""Membership service: join + graceful-leave protocol over the DCN
control plane.

The reference's join path rides UD multicast: a joiner mcasts JOIN,
the leader assigns a slot or up-sizes the configuration and appends a
CONFIG entry, and the reply (CFG_REPLY: idx, cid, head) arrives once the
entry applies (ud_join_cluster dare_ibv_ud.c:952-967,
handle_server_join_request :972-1068, ud_send_clt_reply :1451-1498).

Our control plane is TCP to any replica's PeerServer: non-leaders answer
NOT_LEADER with a hint (the joiner "multicasts" by iterating peers), the
leader blocks the join connection until the CONFIG entry applies, then
replies with the assigned slot, the new Cid, and the full peer list.
Log/state catch-up needs no separate handshake: the leader's replication
path adjusts the joiner from scratch and pushes a snapshot if the
joiner is behind the pruned head (Node._replicate).

Refusals are TYPED (the reference's CFG_REPLY carries only success):
``ST_RETRY`` means the condition is transient (a resize already in
flight, the log ring momentarily full) — the joiner backs off with
jitter and retries inside its deadline; ``ST_REFUSED`` is permanent for
the current configuration (the wanted slot is bound to a different
address — the "removed, rejoin refused" answer — or the group is at
protocol capacity) and surfaces as :class:`JoinRefusedError` instead of
an indistinguishable timeout.

Graceful leave (OP_LEAVE) is the operator-initiated counterpart of the
failure detector's auto-removal: the leader commits the removal CONFIG
entry (payload ``leave <slot>`` — the reason is replicated, so the
drained member recognizes an intentional removal when it applies the
entry), the drained replica stops voting/acking and exits clean, and
its next incarnation re-enters through the join protocol with a fresh
incarnation (snapshot catch-up included).
"""

from __future__ import annotations

import json
import random
import socket
import time
from typing import Optional

from apus_tpu.core.cid import Cid
from apus_tpu.parallel import wire
from apus_tpu.runtime.client import ST_NOT_LEADER, ST_TIMEOUT, _not_leader

OP_JOIN = wire.OP_JOIN
#: operator-initiated graceful removal (see module docstring):
#: payload u8 slot [+ u8 mode]; mode 0 = commit the removal at the
#: leader (NOT_LEADER redirect otherwise), mode 1 = drain notification
#: delivered to the (ex-)member itself after the removal committed.
OP_LEAVE = 21

#: Typed membership statuses (beyond the client plane's OK/NOT_LEADER/
#: TIMEOUT): transient refusal — back off and retry — vs. permanent
#: refusal for the current configuration (reason blob follows).
ST_RETRY = 6
ST_REFUSED = 7


class JoinRefusedError(RuntimeError):
    """The leader answered the join with a PERMANENT typed refusal
    (e.g. "slot_bound": the wanted slot is owned by a different live
    address — a removed server whose identity was reassigned must not
    rejoin as that slot)."""


class LeaveRefusedError(RuntimeError):
    """The leader answered OP_LEAVE with a permanent typed refusal
    (e.g. "quorum_floor": removing the member would leave fewer
    members than the unchanged size-denominator quorum — a config that
    could never commit or elect again)."""


def make_membership_ops(daemon, node=None) -> dict:
    """Extra PeerServer ops: JOIN + LEAVE (run on per-connection
    threads).  ``node`` binds the handlers to one consensus group's
    node (multi-group daemons admit a joiner into EVERY group — the
    joiner runs the join protocol per group, each against that group's
    leader); None = the primary group."""
    node = node if node is not None else daemon.node

    def join(r: wire.Reader) -> bytes:
        addr = r.blob().decode()
        want_slot = r.u8() if r.remaining else None
        with daemon.lock:
            if want_slot is not None and node.is_leader \
                    and node.cid.contains(want_slot) \
                    and want_slot < len(daemon.spec.peers) \
                    and daemon.spec.peers[want_slot] == addr:
                # Already a member at its OWN address: idempotent
                # admission, no CONFIG.  This is the multi-group
                # rejoin case — a daemon evicted from SOME groups
                # rejoins every group, and a group whose failure
                # detector never fired still lists the slot (bound to
                # this exact address, so the stranger-demands-a-bound-
                # slot refusal below does not apply).
                import dataclasses as _dc
                daemon.logger.info("JOIN[g%d] %s already member at "
                                   "slot %d (idempotent)", node.gid,
                                   addr, want_slot)
                return (wire.u8(wire.ST_OK) + wire.u8(want_slot)
                        + wire.encode_cid(node.cid)
                        + wire.blob(json.dumps(
                            daemon.spec.peers).encode())
                        + wire.blob(json.dumps(
                            _dc.asdict(daemon.spec)).encode()))
            pj = node.handle_join(addr, want_slot=want_slot)
            reason = node.last_join_refusal
        if pj is None:
            if reason is None:
                return _not_leader(daemon, node=node)
            # We ARE the leader but refused: answer typed, never
            # NOT_LEADER — a hint-chase for a leader the joiner
            # already found stalls it for its whole deadline.
            transient = reason in node.TRANSIENT_REFUSALS
            return (wire.u8(ST_RETRY if transient else ST_REFUSED)
                    + wire.blob(reason.encode()))
        deadline = time.monotonic() + daemon.client_op_timeout
        with daemon.commit_cond:
            while True:
                if pj.refused:
                    # The join's CONFIG entry applied, but the slot is
                    # not in the applied configuration (a resize abort
                    # raced it): transient — retry from scratch.
                    return (wire.u8(ST_RETRY)
                            + wire.blob(b"resize_aborted"))
                if pj.done:
                    daemon.logger.info("JOIN[g%d] %s -> slot %d (%r)",
                                       node.gid, addr, pj.slot, node.cid)
                    # The reply carries the full peer table AND the
                    # cluster spec: a seed-bootstrapped joiner (daemon
                    # --seed host:port, no config file) learns the
                    # timing envelope and everything else it needs from
                    # this one message — the discovery role the
                    # reference's mcast CFG_REPLY plays
                    # (dare_ibv_ud.c:1451-1498).
                    import dataclasses as _dc
                    return (wire.u8(wire.ST_OK) + wire.u8(pj.slot)
                            + wire.encode_cid(node.cid)
                            + wire.blob(json.dumps(
                                daemon.spec.peers).encode())
                            + wire.blob(json.dumps(
                                _dc.asdict(daemon.spec)).encode()))
                if not node.is_leader:
                    return _not_leader(daemon, node=node)
                left = deadline - time.monotonic()
                if left <= 0:
                    return wire.u8(ST_TIMEOUT)
                daemon.commit_cond.wait(min(left, 0.05))

    def leave(r: wire.Reader) -> bytes:
        slot = r.u8()
        mode = r.u8() if r.remaining else 0
        if mode == 1:
            # Drain notification: the removal of OUR slot has been
            # committed cluster-wide (the sender saw the leader's OK).
            # Covers the race where the removal committed without this
            # replica ever receiving the CONFIG entry (commit needs
            # only a quorum); usually the replicated "leave" marker
            # got here first and this is an idempotent no-op.
            if slot != daemon.idx:
                return wire.u8(ST_REFUSED) + wire.blob(b"not_my_slot")
            daemon.begin_drain("operator notify")
            return wire.u8(wire.ST_OK)
        with daemon.lock:
            pl = node.handle_leave(slot)
        if pl is None:
            return _not_leader(daemon, node=node)
        if isinstance(pl, str):
            transient = pl in node.TRANSIENT_REFUSALS
            return (wire.u8(ST_RETRY if transient else ST_REFUSED)
                    + wire.blob(pl.encode()))
        deadline = time.monotonic() + daemon.client_op_timeout
        with daemon.commit_cond:
            while True:
                if pl.done:
                    daemon.logger.info("LEAVE[g%d] slot %d committed "
                                       "(%r)", node.gid, slot, node.cid)
                    return wire.u8(wire.ST_OK) + wire.u8(slot)
                if not node.is_leader:
                    return _not_leader(daemon, node=node)
                left = deadline - time.monotonic()
                if left <= 0:
                    return wire.u8(ST_TIMEOUT)
                daemon.commit_cond.wait(min(left, 0.05))

    return {OP_JOIN: join, OP_LEAVE: leave}


def request_join(peers: list[str], my_addr: str,
                 timeout: float = 15.0,
                 want_slot: Optional[int] = None) -> tuple[int, Cid, list[str]]:
    """Joiner side: find the leader and request admission.  Returns
    (slot, cid, full peer list).  Retries across redirects/elections
    with jittered exponential backoff under a TOTAL deadline — a
    partitioned or flapping seed peer can no longer stall the joiner
    beyond ``timeout``.  ``want_slot`` requests slot affinity
    (recovered-server rejoin): the leader admits at that exact slot or
    answers a typed refusal — permanent refusals ("removed, rejoin
    refused": the slot is bound to another address) raise
    :class:`JoinRefusedError` immediately instead of burning the
    deadline.

    ``peers`` may be a SINGLE seed address (discovery bootstrap, the
    mcast-JOIN analog, dare_ibv_ud.c:952-1068): a non-leader seed
    redirects via the NOT_LEADER hint, and the admission reply carries
    the full peer table — the joiner needs nothing else up front.  Use
    :func:`request_join_spec` to also receive the cluster spec."""
    slot, cid, full_peers, _ = request_join_spec(peers, my_addr,
                                                 timeout, want_slot)
    return slot, cid, full_peers


def request_join_spec(peers: list[str], my_addr: str,
                      timeout: float = 15.0,
                      want_slot: Optional[int] = None
                      ) -> tuple[int, Cid, list[str], Optional[dict]]:
    """request_join returning additionally the cluster-spec dict the
    leader serialized into the reply (None from pre-spec leaders)."""
    payload = wire.u8(OP_JOIN) + wire.blob(my_addr.encode())
    if want_slot is not None:
        payload += wire.u8(want_slot)
    deadline = time.monotonic() + timeout
    candidates = list(peers)
    rng = random.Random()
    backoff = _Backoff(rng)
    i = 0
    while time.monotonic() < deadline:
        target = candidates[i % len(candidates)]
        i += 1
        resp = _roundtrip(target, payload, deadline)
        if resp is None:
            backoff.sleep(deadline)
            continue
        st = resp[0]
        if st == wire.ST_OK:
            r = wire.Reader(resp[1:])
            slot = r.u8()
            cid = wire.decode_cid(r)
            full_peers = json.loads(r.blob().decode())
            spec_dict = (json.loads(r.blob().decode())
                         if r.remaining else None)
            return slot, cid, full_peers, spec_dict
        if st == ST_NOT_LEADER:
            hint = wire.Reader(resp[1:]).blob().decode() \
                if len(resp) > 1 else ""
            if hint and hint not in candidates:
                candidates.append(hint)
            if hint:
                i = candidates.index(hint)
                backoff.reset()          # fresh lead: don't punish it
            time.sleep(0.01)
            continue
        if st == ST_REFUSED:
            reason = _reason(resp)
            raise JoinRefusedError(
                f"join of {my_addr} refused by the leader: {reason} "
                f"(want_slot={want_slot})")
        # ST_RETRY (typed transient refusal) / ST_TIMEOUT / transient:
        # jittered exponential backoff inside the deadline.
        backoff.sleep(deadline)
    raise TimeoutError(f"join of {my_addr} not admitted in {timeout}s")


def request_join_group(peers: list[str], my_addr: str, gid: int,
                       want_slot: int,
                       timeout: float = 15.0) -> Cid:
    """Joiner side for ONE extra consensus group (gid > 0): run the
    join protocol against THAT group's leader (group-wrapped OP_JOIN,
    chasing that group's NOT_LEADER hints) at exactly ``want_slot`` —
    slots must agree across groups, since a daemon's identity (peer
    table index, transport endpoint) is slot-keyed.  Returns the
    group's admission cid."""
    payload = (wire.u8(wire.OP_GROUP) + wire.u8(gid)
               + wire.u8(OP_JOIN) + wire.blob(my_addr.encode())
               + wire.u8(want_slot))
    deadline = time.monotonic() + timeout
    candidates = list(peers)
    rng = random.Random()
    backoff = _Backoff(rng)
    i = 0
    while time.monotonic() < deadline:
        target = candidates[i % len(candidates)]
        i += 1
        resp = _roundtrip(target, payload, deadline)
        if resp is None:
            backoff.sleep(deadline)
            continue
        st = resp[0]
        if st == wire.ST_OK:
            r = wire.Reader(resp[1:])
            slot = r.u8()
            cid = wire.decode_cid(r)
            if slot != want_slot:
                raise JoinRefusedError(
                    f"group {gid} admitted {my_addr} at slot {slot} != "
                    f"wanted {want_slot}")
            return cid
        if st == ST_NOT_LEADER:
            hint = wire.Reader(resp[1:]).blob().decode() \
                if len(resp) > 1 else ""
            if hint and hint not in candidates:
                candidates.append(hint)
            if hint:
                i = candidates.index(hint)
                backoff.reset()
            time.sleep(0.01)
            continue
        if st == ST_REFUSED:
            raise JoinRefusedError(
                f"group {gid} join of {my_addr} refused: "
                f"{_reason(resp)} (want_slot={want_slot})")
        backoff.sleep(deadline)
    raise TimeoutError(f"group {gid} join of {my_addr} not admitted "
                       f"in {timeout}s")


def request_join_all_groups(peers: list[str], my_addr: str, slot: int,
                            n_groups: int,
                            timeout: float = 30.0) -> dict:
    """Join every EXTRA group (1..n_groups-1) at ``slot`` (group 0's
    assignment).  Returns {gid: cid} — possibly MISSING groups whose
    join timed out (a group mid-election/mid-resize under churn can
    stall past any reasonable boot budget; the daemon finishes those
    admissions in the background via
    ``ReplicaDaemon.retry_group_joins`` instead of dying at boot).  A
    PERMANENT refusal still propagates — the daemon must not serve a
    group it was denied."""
    cids = {}
    for gid in range(1, n_groups):
        try:
            cids[gid] = request_join_group(peers, my_addr, gid, slot,
                                           timeout=timeout)
        except TimeoutError:
            continue                     # deferred (retry thread)
    return cids


def request_leave(peers: list[str], slot: int,
                  timeout: float = 15.0,
                  victim_addr: Optional[str] = None,
                  groups: int = 1) -> bool:
    """Operator side of the graceful leave: find the leader, have it
    commit the removal of ``slot``, then best-effort notify the
    drained replica (mode-1 OP_LEAVE) so it exits clean even if the
    removal committed without reaching it.  Returns True once the
    removal is committed.  Raises :class:`LeaveRefusedError` on a
    permanent typed refusal and TimeoutError past the deadline.

    ``groups > 1``: the removal is committed in EVERY consensus group
    — group 0 first (its "leave" marker is what drains the victim
    daemon), then each extra group via group-wrapped OP_LEAVE against
    THAT group's leader.  An extra group that already evicted the slot
    (auto-removal raced) answers done idempotently."""
    deadline = time.monotonic() + timeout
    candidates = [p for p in peers if p]
    if victim_addr is None and slot < len(peers):
        victim_addr = peers[slot]
    rng = random.Random()

    def _leave_one(payload: bytes, tag: str) -> None:
        backoff = _Backoff(rng)
        i = 0
        cands = list(candidates)
        while time.monotonic() < deadline:
            target = cands[i % len(cands)]
            i += 1
            resp = _roundtrip(target, payload, deadline)
            if resp is None:
                backoff.sleep(deadline)
                continue
            st = resp[0]
            if st == wire.ST_OK:
                return
            if st == ST_NOT_LEADER:
                hint = wire.Reader(resp[1:]).blob().decode() \
                    if len(resp) > 1 else ""
                if hint and hint not in cands:
                    cands.append(hint)
                if hint:
                    i = cands.index(hint)
                    backoff.reset()
                time.sleep(0.01)
                continue
            if st == ST_REFUSED:
                raise LeaveRefusedError(
                    f"leave of slot {slot} ({tag}) refused: "
                    f"{_reason(resp)}")
            backoff.sleep(deadline)
        raise TimeoutError(f"leave of slot {slot} ({tag}) not "
                           f"committed in {timeout}s")

    _leave_one(wire.u8(OP_LEAVE) + wire.u8(slot), "g0")
    for gid in range(1, max(1, groups)):
        _leave_one(wire.u8(wire.OP_GROUP) + wire.u8(gid)
                   + wire.u8(OP_LEAVE) + wire.u8(slot), f"g{gid}")
    if victim_addr:
        _notify_drained(victim_addr, slot)
    return True


def _notify_drained(victim_addr: str, slot: int,
                    timeout: float = 2.0) -> bool:
    """Mode-1 OP_LEAVE to the drained replica itself (best effort: the
    replicated "leave" marker usually got there first; a dead victim
    simply misses a redundant notification)."""
    try:
        resp = _roundtrip(victim_addr,
                          wire.u8(OP_LEAVE) + wire.u8(slot) + wire.u8(1),
                          time.monotonic() + timeout)
    except Exception:               # noqa: BLE001
        return False
    return bool(resp) and resp[0] == wire.ST_OK


class _Backoff:
    """Jittered exponential backoff capped per attempt AND by the
    caller's absolute deadline (the join/leave retry discipline)."""

    BASE = 0.05
    CAP = 1.0

    def __init__(self, rng: random.Random):
        self.rng = rng
        self.cur = self.BASE

    def reset(self) -> None:
        self.cur = self.BASE

    def sleep(self, deadline: float) -> None:
        d = min(self.cur * self.rng.uniform(0.5, 1.5),
                max(0.0, deadline - time.monotonic()))
        if d > 0:
            time.sleep(d)
        self.cur = min(self.cur * 2.0, self.CAP)


def _reason(resp: bytes) -> str:
    try:
        return wire.Reader(resp[1:]).blob().decode() or "unspecified"
    except (ValueError, UnicodeDecodeError):
        return "unspecified"


def _roundtrip(addr: str, payload: bytes,
               deadline: float) -> Optional[bytes]:
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection(
                (host, int(port)),
                timeout=max(0.05, min(2.0, deadline - time.monotonic()))) \
                as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(max(0.05, deadline - time.monotonic()))
            conn.sendall(wire.frame(payload))
            return wire.read_frame(conn)
    except (OSError, ConnectionError, ValueError):
        return None
