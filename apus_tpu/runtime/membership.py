"""Membership service: join protocol over the DCN control plane.

The reference's join path rides UD multicast: a joiner mcasts JOIN,
the leader assigns a slot or up-sizes the configuration and appends a
CONFIG entry, and the reply (CFG_REPLY: idx, cid, head) arrives once the
entry applies (ud_join_cluster dare_ibv_ud.c:952-967,
handle_server_join_request :972-1068, ud_send_clt_reply :1451-1498).

Our control plane is TCP to any replica's PeerServer: non-leaders answer
NOT_LEADER with a hint (the joiner "multicasts" by iterating peers), the
leader blocks the join connection until the CONFIG entry applies, then
replies with the assigned slot, the new Cid, and the full peer list.
Log/state catch-up needs no separate handshake: the leader's replication
path adjusts the joiner from scratch and pushes a snapshot if the
joiner is behind the pruned head (Node._replicate).
"""

from __future__ import annotations

import json
import socket
import time
from typing import Optional

from apus_tpu.core.cid import Cid
from apus_tpu.parallel import wire
from apus_tpu.runtime.client import ST_NOT_LEADER, ST_TIMEOUT, _not_leader

OP_JOIN = wire.OP_JOIN


def make_membership_ops(daemon) -> dict:
    """Extra PeerServer op: JOIN (runs on a per-connection thread)."""

    def join(r: wire.Reader) -> bytes:
        addr = r.blob().decode()
        want_slot = r.u8() if r.remaining else None
        with daemon.lock:
            pj = daemon.node.handle_join(addr, want_slot=want_slot)
        if pj is None:
            return _not_leader(daemon)
        deadline = time.monotonic() + daemon.client_op_timeout
        with daemon.commit_cond:
            while True:
                if pj.done:
                    daemon.logger.info("JOIN %s -> slot %d (%r)", addr,
                                       pj.slot, daemon.node.cid)
                    # The reply carries the full peer table AND the
                    # cluster spec: a seed-bootstrapped joiner (daemon
                    # --seed host:port, no config file) learns the
                    # timing envelope and everything else it needs from
                    # this one message — the discovery role the
                    # reference's mcast CFG_REPLY plays
                    # (dare_ibv_ud.c:1451-1498).
                    import dataclasses as _dc
                    return (wire.u8(wire.ST_OK) + wire.u8(pj.slot)
                            + wire.encode_cid(daemon.node.cid)
                            + wire.blob(json.dumps(
                                daemon.spec.peers).encode())
                            + wire.blob(json.dumps(
                                _dc.asdict(daemon.spec)).encode()))
                if not daemon.node.is_leader:
                    return _not_leader(daemon)
                left = deadline - time.monotonic()
                if left <= 0:
                    return wire.u8(ST_TIMEOUT)
                daemon.commit_cond.wait(min(left, 0.05))

    return {OP_JOIN: join}


def request_join(peers: list[str], my_addr: str,
                 timeout: float = 15.0,
                 want_slot: Optional[int] = None) -> tuple[int, Cid, list[str]]:
    """Joiner side: find the leader and request admission.  Returns
    (slot, cid, full peer list).  Retries across redirects/elections.
    ``want_slot`` requests slot affinity (recovered-server rejoin): the
    leader admits at that exact slot or refuses.

    ``peers`` may be a SINGLE seed address (discovery bootstrap, the
    mcast-JOIN analog, dare_ibv_ud.c:952-1068): a non-leader seed
    redirects via the NOT_LEADER hint, and the admission reply carries
    the full peer table — the joiner needs nothing else up front.  Use
    :func:`request_join_spec` to also receive the cluster spec."""
    slot, cid, full_peers, _ = request_join_spec(peers, my_addr,
                                                 timeout, want_slot)
    return slot, cid, full_peers


def request_join_spec(peers: list[str], my_addr: str,
                      timeout: float = 15.0,
                      want_slot: Optional[int] = None
                      ) -> tuple[int, Cid, list[str], Optional[dict]]:
    """request_join returning additionally the cluster-spec dict the
    leader serialized into the reply (None from pre-spec leaders)."""
    payload = wire.u8(OP_JOIN) + wire.blob(my_addr.encode())
    if want_slot is not None:
        payload += wire.u8(want_slot)
    deadline = time.monotonic() + timeout
    candidates = list(peers)
    i = 0
    while time.monotonic() < deadline:
        target = candidates[i % len(candidates)]
        i += 1
        resp = _roundtrip(target, payload, deadline)
        if resp is None:
            time.sleep(0.05)
            continue
        st = resp[0]
        if st == wire.ST_OK:
            r = wire.Reader(resp[1:])
            slot = r.u8()
            cid = wire.decode_cid(r)
            full_peers = json.loads(r.blob().decode())
            spec_dict = (json.loads(r.blob().decode())
                         if r.remaining else None)
            return slot, cid, full_peers, spec_dict
        if st == ST_NOT_LEADER:
            hint = wire.Reader(resp[1:]).blob().decode() \
                if len(resp) > 1 else ""
            if hint and hint not in candidates:
                candidates.append(hint)
            if hint:
                i = candidates.index(hint)
            time.sleep(0.01)
            continue
        time.sleep(0.05)      # ST_TIMEOUT / transient: retry
    raise TimeoutError(f"join of {my_addr} not admitted in {timeout}s")


def _roundtrip(addr: str, payload: bytes,
               deadline: float) -> Optional[bytes]:
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection(
                (host, int(port)),
                timeout=max(0.05, min(2.0, deadline - time.monotonic()))) \
                as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(max(0.05, deadline - time.monotonic()))
            conn.sendall(wire.frame(payload))
            return wire.read_frame(conn)
    except (OSError, ConnectionError, ValueError):
        return None
