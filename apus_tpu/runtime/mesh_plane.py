"""Multi-controller device plane: process-per-replica commit over a
global ``jax.distributed`` mesh, with epoch-based RE-FORMATION.

The reference's one-sided data plane runs INSIDE every server process —
each machine's DARE thread posts RDMA writes from its own address space
(``rc_write_remote_logs`` called from the server's commit loop,
dare_ibv_rc.c:1870-1948).  The in-process ``DeviceCommitRunner``
(runtime.device_plane) gives that shape to daemons sharing ONE process;
THIS module gives it to the production deployment: one OS process per
replica (runtime.proc / runtime.daemon), each owning one device of a
global ``jax.sharding.Mesh`` glued together by ``jax.distributed`` —
exactly how a multi-host TPU pod runs one JAX program per host.

How a round works (multi-controller SPMD):

- Every process dispatches the SAME compiled program (the pipelined
  commit step of ops.commit with ``verify_round=True``).  The leader's
  process stages its window into ITS local input shard; followers stage
  zeros.  The in-step ``pmax`` broadcast then moves the batch
  device-to-device over the interconnect — followers' HOST code never
  touches the payload, which is precisely the reference's one-sided
  write semantics (followers passive on the replication path).
- Followers learn WHAT to dispatch from a round DESCRIPTOR the leader
  sends over the TCP control plane (a PeerServer extra op, OP_MESH) —
  control metadata (term, end0, masks), never entry payload.  This
  mirrors the reference's UD-control/RC-data split.
- Each process reads results from its OWN addressable shard — no
  collective on the read path (the rc_recover_log analog of reading
  back the memory the RDMA writes landed in).

Global program order (the multi-controller invariant): the backend
pairs collectives across processes by dispatch order, so every process
must issue the identical sequence of identical-shaped programs.  Three
rules enforce it:

1. ONE window shape.  Every dispatch is ``spec.mesh_depth`` rounds of
   one batch (partial backlog is NOOP-padded by the driver), so
   mismatched-shape pairings are structurally impossible.
2. ONE dispatch authority per process — the worker thread — consuming
   an ordered queue fed locally (leader) and by descriptor arrivals
   (followers).
3. NEVER drop, always POISON — within an epoch.  A descriptor that is
   stale (old generation, or a term below the daemon's current term)
   is still dispatched — pairing! — but with a poisoned round
   identity, so the in-step ``verify_round`` check refuses the write
   EVERYWHERE and the round decides nothing.  This is the in-step
   form of QP-reset fencing (dare_ibv_rc.c:2156-2255): the deposed
   leader's write executes against the fabric but cannot land or mint
   a commit.  ACROSS epochs the rule inverts: a descriptor from
   another plane epoch is NACKed (its clique is globally defunct — a
   member only reforms once the old plane is dead everywhere, so
   there is no live collective left to pair with), which promptly
   kills the stale sender's feed and forces it through re-formation.

RE-FORMATION (plane epochs) — the capability the reference gets from
its RC re-handshake (a restarted server re-runs RC_SYN/SYNACK/ACK and
the leader resumes one-sided replication to it, dare_ibv_ud.c:1098-1416,
QPs re-granted dare_ibv_rc.c:2195-2255):

- A *plane epoch* is one ``jax.distributed`` clique lifetime.  Epoch 0
  is the initial bring-up.  When the plane degrades (member death,
  wedge, election-budget poisoning) and the consensus membership
  re-stabilizes — dead member evicted, or rejoined and caught up — the
  LEADER rebuilds the clique under a new epoch: a fresh coordination-
  service instance (``MeshCoordinator.prepare``), a fresh gloo
  rendezvous, fresh shards, a fresh worker thread.
- The clique is the sorted list of live mesh-capable slots; mesh row r
  is ``members[r]``, so a shrunk clique {0,2} of group {0,1,2} still
  owns commit (2-of-3 quorum rides the device; the third member
  catches up over the TCP plane — the reference's RDMA-to-live-
  followers shape).  Quorum *thresholds* stay derived from the full
  configuration sizes (masking shrinks only the numerator).
- Teardown is validated-empirical (jaxlib 0.9, probed): drop array +
  executable refs, ``jax.clear_caches()``, shut down the distributed
  client (stops its error poller — the client of a deleted service
  otherwise LOG(FATAL)s the process), ``xla_bridge._clear_backends()``,
  then re-init.  A collective STUCK in the old backend (wedged peer)
  does not block this: the old client lingers ref-held by its stuck
  execution and is reaped when gloo times out; the stuck worker thread
  is abandoned (each epoch has its own worker + queue).
- The incarnation rule (a crashed replica's NEW process must never
  re-join a service instance its dead incarnation was part of — the
  service rejects it and the runtime terminates the healthy members)
  becomes per-epoch: the durable marker records the last epoch this
  slot joined; a restarted daemon comes up DETACHED and participates
  only from the next epoch on, which the leader's reformer assigns.

Election safety (why device acks may count toward commit at all): a
follower's vote must cover every entry its shard ever acked, or a
deposed leader could commit through shard acks the new leader's
election never saw.  Two mechanisms close this:

- The worker decides poisoning UNDER THE DAEMON LOCK with a term check
  and registers the window handle in ``_outstanding`` *before*
  releasing it; the dispatch itself then runs OUTSIDE the daemon lock
  (a dispatch can block for minutes inside a wedged collective —
  holding the lock there would wedge the daemon's tick thread and
  take the replica's TCP consensus down with the plane).  Any vote is
  serialized against this by the same lock: either the vote's term
  bump happens first (the worker then poisons the round), or the
  handle is registered first (the vote is vetoed until it resolves
  and the drain absorbs its rows).
- ``quiesce_ready()`` — consulted by the driver's pre-election hook
  before ANY vote is granted or campaign starts.  While a window this
  process dispatched is still executing, the vote is VETOED (deferred
  a tick — never blocked in place); once all windows are executed,
  the shard drain absorbs the landed rows into the host log and the
  vote proceeds.  The veto is BOUNDED: past
  ``spec.mesh_election_budget`` (~100 ms) the plane is POISONED —
  declared dead, vote proceeds, re-formation restores the plane later
  — the immediate-revocation analog of QP reset
  (dare_ibv_rc.c:2156-2189), affordable now that a poisoned plane is
  not permanently lost.  (Pre-re-formation this wait rode the
  backend's own error surfacing, ~0.5-5 s — the mesh-envelope
  failover inflation VERDICT r4 flagged.)

Failure semantics (the ICI-slice model): the distributed runtime is
brought up with effectively-infinite coordination heartbeats — the
default behavior (terminating every process ~100 s after one dies;
probed empirically on jaxlib 0.9) would turn a single replica crash
into a total outage.  Member death is detected the way the data plane
itself sees it: the collective errors out promptly and CATCHABLY
(connection reset), the worker deactivates the plane, and the daemon
continues on the TCP plane — the reference degrades the same way when
a NIC dies and its QPs error out (WC error taxonomy,
dare_ibv_rc.c:3202-3314).  A degraded plane no longer stays down for
the cluster's lifetime: the reformer brings it back under the next
epoch once membership re-stabilizes.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import socket
import threading
import time
from typing import Callable, Optional

import numpy as np

from apus_tpu.core.log import LogEntry
from apus_tpu.core.quorum import quorum_size
from apus_tpu.parallel import wire

#: PeerServer extra-op for mesh-plane descriptors (leader -> follower).
OP_MESH = 13
_SUB_RESET = 0
_SUB_ROUND = 1
_SUB_REFORM = 2

#: MeshCoordinator control ops.
_COORD_PREPARE = 1

#: Effectively-infinite coordination heartbeat (seconds): liveness is
#: the consensus layer's job; the device plane learns of death from
#: collective errors (see module docstring).
_NO_HEARTBEAT = 10 ** 7


def _make_runtime_service(addr: str, n: int):
    """jaxlib's distributed-runtime service across jax versions:
    >= 0.5 exposes the C API as ``jax._src.lib._jax`` and takes
    ``heartbeat_timeout``; <= 0.4.x names the module ``xla_extension``
    and splits the knob into ``heartbeat_interval`` (x a default
    missing-count).  Either spelling of 10^7 s means the same thing
    here: never evict on heartbeat."""
    try:
        from jax._src.lib import _jax
        return _jax.get_distributed_runtime_service(
            addr, n, heartbeat_timeout=_NO_HEARTBEAT, shutdown_timeout=5)
    except ImportError:
        from jax._src.lib import xla_extension
        return xla_extension.get_distributed_runtime_service(
            addr, n, heartbeat_interval=_NO_HEARTBEAT, shutdown_timeout=5)


def _make_runtime_client(coordinator: str, process_id: int,
                         init_timeout: int):
    """Client half of :func:`_make_runtime_service` (same version
    split)."""
    try:
        from jax._src.lib import _jax
        return _jax.get_distributed_runtime_client(
            coordinator, process_id, init_timeout=init_timeout,
            heartbeat_timeout=_NO_HEARTBEAT,
            shutdown_on_destruction=False, use_compression=True)
    except ImportError:
        from jax._src.lib import xla_extension
        return xla_extension.get_distributed_runtime_client(
            coordinator, process_id, init_timeout=init_timeout,
            heartbeat_interval=_NO_HEARTBEAT,
            shutdown_on_destruction=False, use_compression=True)


# -- coordinator ------------------------------------------------------------


class MeshCoordinator:
    """Plane-epoch control server + coordination-service factory.

    Lives in its OWN process, outside every replica: a replica that
    hosted the coordination service would couple the whole mesh's fate
    to its own — the runtime's error-polling treats "coordination
    service unreachable" as LOG(FATAL) and terminates every member
    (observed empirically), turning one replica crash into a total
    outage.  A dedicated coordinator is never a fault-injection
    target, exactly like the reference's IB subnet manager is not one
    of the replicas.

    Protocol (wire-framed over TCP at ``addr``):
      PREPARE(epoch u64, n u8) -> ST_OK + blob(service host:port)
        Idempotent per epoch: the first call creates a fresh
        ``jax.distributed`` service instance for ``n`` processes on an
        ephemeral port; repeats return the same address (every clique
        member PREPAREs epoch 0 independently at bring-up; later
        epochs are PREPAREd by the leader's reformer).  A repeat with
        a DIFFERENT n is refused — a half-joined service instance
        cannot change size.

    Old service instances are kept alive until ``keep`` newer epochs
    exist (probed: deleting a service whose clients haven't detached
    LOG(FATAL)s them; by ``keep`` epochs later any straggler is a
    wedged, already-evicted incarnation whose termination is the slice
    reset it needs anyway)."""

    def __init__(self, addr: str, keep: int = 4):
        host, port = addr.rsplit(":", 1)
        self.host = host
        self.keep = keep
        self._lock = threading.Lock()
        #: epoch -> (service, n, "host:port")
        self._epochs: dict[int, tuple] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(32)
        self._stop = threading.Event()

    @property
    def addr(self) -> str:
        h, p = self._sock.getsockname()
        return f"{h}:{p}"

    def _prepare(self, epoch: int, n: int) -> Optional[str]:
        with self._lock:
            have = self._epochs.get(epoch)
            if have is not None:
                return have[2] if have[1] == n else None
            # Ephemeral port, bind-then-close reservation (free_port
            # shape): the service API needs an explicit port.
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind((self.host, 0))
            port = s.getsockname()[1]
            s.close()
            addr = f"{self.host}:{port}"
            svc = _make_runtime_service(addr, n)
            self._epochs[epoch] = (svc, n, addr)
            print(f"APUS-MESH-COORDINATOR epoch {epoch} at {addr} for "
                  f"{n} processes", flush=True)
            # GC epochs more than `keep` behind the newest.
            newest = max(self._epochs)
            for e in [e for e in self._epochs if e <= newest - self.keep]:
                del self._epochs[e]
            return addr

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            while True:
                payload = wire.read_frame(conn)
                if payload is None:
                    return
                r = wire.Reader(payload)
                if r.u8() != _COORD_PREPARE:
                    conn.sendall(wire.frame(wire.u8(wire.ST_ERROR)))
                    continue
                epoch, n = r.u64(), r.u8()
                addr = self._prepare(epoch, n)
                if addr is None:
                    conn.sendall(wire.frame(wire.u8(wire.ST_ERROR)))
                else:
                    conn.sendall(wire.frame(
                        wire.u8(wire.ST_OK) + wire.blob(addr.encode())))
        except Exception:                             # noqa: BLE001
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def serve_forever(self) -> None:
        print(f"APUS-MESH-COORDINATOR ready at {self.addr}", flush=True)
        # Orphan watchdog (same contract as the replica daemon's): the
        # env var carries the HARNESS pid; when our parent is no longer
        # that pid the harness died without stop() — exit instead of
        # serving a dead mesh forever.
        try:
            harness_pid = int(os.environ.get("APUS_EXIT_IF_ORPHANED", ""))
        except ValueError:
            harness_pid = 0
        if harness_pid > 0:
            def _watch():
                while not self._stop.is_set():
                    if os.getppid() != harness_pid:
                        print("harness gone; coordinator exiting "
                              "(APUS_EXIT_IF_ORPHANED)", flush=True)
                        os._exit(0)
                    time.sleep(2.0)
            threading.Thread(target=_watch, daemon=True).start()
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def serve_coordinator(addr: str, n_processes: int) -> None:
    """Host the mesh coordination control server (one per cluster,
    outside every replica).  ``n_processes`` is advisory — each
    epoch's size arrives in its PREPARE.  Blocks forever (run it under
    a supervisor)."""
    del n_processes
    MeshCoordinator(addr).serve_forever()


def prepare_epoch(coordinator: str, epoch: int, n: int,
                  timeout: float = 5.0, retry_for: float = 0.0) -> str:
    """Ask the coordinator for epoch ``epoch``'s coordination-service
    address (creating the service if this is the first ask).
    ``retry_for`` > 0 retries connection failures for that many seconds
    — replica daemons and the coordinator launch concurrently, so the
    first PREPARE can race the coordinator's bind."""
    host, port = coordinator.rsplit(":", 1)
    deadline = time.monotonic() + retry_for
    while True:
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=timeout) as s:
                s.settimeout(timeout)
                s.sendall(wire.frame(wire.u8(_COORD_PREPARE)
                                     + wire.u64(epoch) + wire.u8(n)))
                resp = wire.read_frame(s)
            break
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.25)
    if resp is None:
        raise ConnectionError("coordinator hung up")
    r = wire.Reader(resp)
    if r.u8() != wire.ST_OK:
        raise RuntimeError(f"coordinator refused epoch {epoch} (n={n})")
    return r.blob().decode()


# -- distributed runtime bring-up/teardown ----------------------------------


def init_distributed(coordinator: str, n_processes: int, process_id: int,
                     platform: str = "cpu",
                     init_timeout: int = 120,
                     host_service: bool = False) -> None:
    """Bring up ``jax.distributed`` with consensus-friendly failure
    semantics (no heartbeat-triggered process termination, no exit-time
    shutdown barrier).  Must run before the first jax backend
    initialization in this process — or after :func:`teardown_
    distributed`.  ``platform='cpu'`` pins the CPU backend (gloo
    collectives) for CPU deployments/tests; '' leaves the platform
    alone (real TPU pods).  ``host_service`` embeds the coordination
    service in process 0 — ONLY for hermetic harnesses (dryrun);
    deployments run a ``MeshCoordinator`` in its own process (see its
    docstring for why)."""
    import os

    import jax

    if platform:
        os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
        # Exactly ONE local device per process: shard r must live on
        # process r.  A virtual multi-device flag inherited from a test
        # environment (xla_force_host_platform_device_count) would give
        # every process N local devices and put the whole mesh's first
        # N shards on process 0.
        flags = os.environ.get("XLA_FLAGS", "")
        scrubbed = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f)
        if scrubbed != flags:
            os.environ["XLA_FLAGS"] = scrubbed
        try:
            jax.config.update("jax_platforms", platform)
            if platform == "cpu":
                try:
                    jax.config.update("jax_num_cpu_devices", 1)
                except AttributeError:
                    # jax <= 0.4.x has no such option; with the
                    # device-count flag scrubbed above the CPU backend
                    # defaults to one local device anyway.
                    pass
                try:
                    # Cross-process CPU collectives must be gloo; on
                    # jax <= 0.4.x the flag defaults to 'none' and the
                    # backend refuses multiprocess computations.
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except AttributeError:
                    pass
        except RuntimeError:
            pass                        # backend already up: caller's bed
    from jax._src import distributed

    state = distributed.global_state
    if state.client is not None:
        return                          # already initialized
    if host_service and process_id == 0:
        state.service = _make_runtime_service(coordinator, n_processes)
    state.client = _make_runtime_client(coordinator, process_id,
                                        init_timeout)
    state.client.connect()
    state.process_id = process_id
    state.num_processes = n_processes
    state.coordinator_address = coordinator


def teardown_distributed() -> None:
    """Tear down this process's ``jax.distributed`` client + backend so
    :func:`init_distributed` can re-rendezvous under a new plane epoch.
    Validated empirically (jaxlib 0.9): non-blocking even with a
    collective STUCK in flight — the old PJRT client stays ref-held by
    its stuck execution and is reaped when gloo times out; the explicit
    ``client.shutdown()`` stops the coordination error poller (whose
    survival past service deletion otherwise LOG(FATAL)s the
    process)."""
    import jax
    from jax._src import distributed, xla_bridge

    jax.clear_caches()
    state = distributed.global_state
    client = state.client
    state.client = None
    state.process_id = 0
    state.num_processes = 1
    state.coordinator_address = None
    if client is not None:
        try:
            client.shutdown()
        except Exception:                             # noqa: BLE001
            pass
        del client
    xla_bridge._clear_backends()
    # _clear_backends drops the backend but NOT every topology cache:
    # process_count/local_devices are @lru_cache'd (jax 0.4.x) and keep
    # answering with the OLD clique's geometry.  A shrunk-clique
    # rebuild then dies inside device_put's multihost assert_equal
    # ("cannot reshape array of size R' into (R, 1)") — every epoch
    # fails identically in ~300 ms and the reformer burns epochs until
    # the test budget expires (the 2 residual tier-1 failures).
    for mod in (jax, xla_bridge):
        for name in ("process_count", "local_devices", "device_count",
                     "process_index"):
            fn = getattr(mod, name, None)
            if fn is not None and hasattr(fn, "cache_clear"):
                fn.cache_clear()


# -- wire payloads ----------------------------------------------------------


@dataclasses.dataclass
class _RoundDesc:
    """Everything a follower needs to dispatch the identical program.
    ``leader`` is the leader's mesh ROW (clique-relative); masks are in
    row space."""

    epoch: int
    gen: int
    seq: int
    leader: int
    term: int
    end0: int
    mask_old: list
    mask_new: list
    q_old: int
    q_new: int

    def encode(self) -> bytes:
        return (wire.u8(OP_MESH) + wire.u8(_SUB_ROUND)
                + wire.u64(self.epoch)
                + wire.u64(self.gen) + wire.u64(self.seq)
                + wire.u8(self.leader) + wire.u64(self.term)
                + wire.u64(self.end0) + wire.u8(self.q_old)
                + wire.u8(self.q_new)
                + wire.blob(bytes(self.mask_old))
                + wire.blob(bytes(self.mask_new)))

    @staticmethod
    def decode(r: wire.Reader) -> "_RoundDesc":
        epoch, gen, seq = r.u64(), r.u64(), r.u64()
        leader, term, end0 = r.u8(), r.u64(), r.u64()
        q_old, q_new = r.u8(), r.u8()
        mask_old = list(r.blob())
        mask_new = list(r.blob())
        return _RoundDesc(epoch, gen, seq, leader, term, end0,
                          mask_old, mask_new, q_old, q_new)


def encode_reform(epoch: int, members: list[int], svc_addr: str,
                  term: int) -> bytes:
    return (wire.u8(OP_MESH) + wire.u8(_SUB_REFORM) + wire.u64(epoch)
            + wire.u64(term) + wire.blob(bytes(members))
            + wire.blob(svc_addr.encode()))


class _PeerFeed:
    """Per-peer FIFO descriptor sender: one dedicated TCP connection to
    the peer's PeerServer, one thread draining a queue of frames.  Any
    send/ack failure marks the feed dead and trips the runner's
    deactivation — a follower that misses one descriptor can never
    rejoin the dispatch sequence (module docstring rule 3 covers
    orderings, not losses)."""

    def __init__(self, addr: tuple, on_dead, timeout: float = 2.0):
        self.addr = addr
        self.on_dead = on_dead
        self.timeout = timeout
        self.q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self.dead = False
        self._sock: Optional[socket.socket] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def send(self, payload: bytes) -> None:
        if not self.dead:
            self.q.put(payload)

    def close(self) -> None:
        self.q.put(None)

    def _run(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                break
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        self.addr, timeout=self.timeout)
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                    self._sock.settimeout(self.timeout)
                self._sock.sendall(wire.frame(item))
                resp = wire.read_frame(self._sock)
                if resp is None or resp[:1] != bytes([wire.ST_OK]):
                    raise ConnectionError(f"mesh feed nack {resp!r}")
            except Exception as e:                    # noqa: BLE001
                self.dead = True
                self.on_dead(self.addr, e)
                break
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass


class MeshWindowHandle:
    """In-flight window handle.  ``commits`` is None from registration
    (pre-dispatch, under the daemon lock) until the dispatch returns —
    observers (quiesce, waits) treat that as not-ready."""

    __slots__ = ("epoch", "gen", "end0", "K", "commits", "poisoned")

    def __init__(self, epoch: int, gen: int, end0: int, K: int,
                 commits=None, poisoned: bool = False):
        self.epoch, self.gen, self.end0, self.K = epoch, gen, end0, K
        self.commits, self.poisoned = commits, poisoned


class MeshCommitRunner:
    """Driver-facing runner whose shards live one-per-process on a
    global mesh.  Exposes the DeviceCommitRunner surface the
    DevicePlaneDriver consumes, plus ``FIXED_WINDOW`` (the single
    window shape every dispatch uses).

    Epoch lifecycle: ``start()`` builds epoch ``min_epoch`` (0 for a
    fresh slot) unless constructed DETACHED (restarted incarnation —
    waits for the leader's reformer to assign the next epoch);
    ``request_reform`` tears the old clique down and rebuilds under a
    new epoch (module docstring, RE-FORMATION)."""

    WIRE_OVERHEAD = 64

    def __init__(self, spec, idx: int, logger=None,
                 detached_epoch: Optional[int] = None):
        self.spec = spec
        self.idx = idx
        self.logger = logger
        self.batch = spec.max_batch
        K = spec.mesh_depth
        self.FIXED_WINDOW = K
        # Driver compatibility: every rung IS the fixed window.
        self.PIPE_DEPTH = K
        self.DEEP_DEPTH = K
        self.window_depths = [K]
        self.use_async_windows = True
        self.slot_bytes = spec.mesh_slot_bytes
        # Ring sized for the deployable async shape by default:
        # MAX_INFLIGHT windows in flight plus one staging must fit
        # ((inflight+K)*B <= S, the driver's capacity gate).
        self.n_slots = spec.mesh_slots or 4 * K * self.batch
        self.lock = threading.Lock()
        #: Plane epoch this process last JOINED (-1 = never); members =
        #: that epoch's clique (slot list, row-ordered).  n_replicas
        #: tracks len(members) for driver/status compatibility.
        if detached_epoch is not None:
            self.epoch = detached_epoch
            self.min_epoch = detached_epoch + 1
            self._detached_start = True
        else:
            self.epoch = -1
            self.min_epoch = 0
            self._detached_start = False
        self.members: list[int] = []
        self.n_replicas = spec.mesh_n
        self._row = -1
        self.building = False
        self._build_target = -1
        self._R = spec.mesh_n           # geometry of the built arrays
        self.generation = 0
        self._worker_gen = 0            # generation of the worker's arrays
        self._term = 0
        self._leader: Optional[int] = None   # leader SLOT
        self._next_end0: Optional[int] = None
        self._seq = 0                   # leader-side descriptor ordinal
        self._expect_seq = 0            # follower-side ordinal (per gen)
        self.stats = {"rounds": 0, "resets": 0, "quorum_fail_rounds": 0,
                      "entries_devplane": 0, "pipelined_dispatches": 0,
                      "poisoned_rounds": 0, "reforms": 0}
        self.depth_histogram: dict[int, int] = {}
        self.pallas_modes: dict[int, Optional[str]] = {K: None}
        self.ready = False
        self.dead = False
        self.death_reason: Optional[str] = None
        #: Marker callback: invoked with the epoch JUST BEFORE this
        #: process connects to its coordination service (the durable
        #: "this incarnation joined epoch E" record the restart logic
        #: keys on — daemon._mesh_marker_write).
        self.on_epoch_join: Optional[Callable[[int], None]] = None
        self._devlog = None
        self._q: "queue.Queue" = queue.Queue()
        #: every dispatched-but-unresolved window (leader AND follower
        #: sides) — quiesce_ready() gates votes on all of them.
        self._outstanding: list[MeshWindowHandle] = []
        self._quiesce_since = None      # unready-window stopwatch
        self._feeds: dict[int, _PeerFeed] = {}
        self._daemon = None             # attach() target
        self._stop = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def attach(self, daemon) -> None:
        """Bind the (single) local daemon: the worker's term checks and
        dispatch ordering are serialized through its lock."""
        self._daemon = daemon

    def start(self) -> None:
        """Kick off the (blocking, collective) distributed bring-up in
        the background; the daemon serves TCP consensus immediately and
        the driver engages once ``ready``.  A DETACHED start (restarted
        incarnation) builds nothing: the old incarnation's epoch cannot
        be re-joined, so this slot waits for the leader's reformer to
        assign the next one."""
        if self._detached_start:
            with self.lock:
                self.dead = True
                self.death_reason = ("restarted incarnation: awaiting "
                                     "re-formation (next epoch >= "
                                     f"{self.min_epoch})")
            if self.logger is not None:
                self.logger.info("mesh plane detached: %s",
                                 self.death_reason)
            return
        err = self.request_reform(self.min_epoch,
                                  list(range(self.spec.mesh_n)),
                                  svc_addr=None, term=0)
        if err is not None:
            self._die(f"initial mesh build refused: {err}")

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)
        for f in self._feeds.values():
            f.close()

    def max_data_bytes(self) -> int:
        return self.slot_bytes - self.WIRE_OVERHEAD

    # -- driver surface: geometry/coverage --------------------------------

    def covers_replica(self, slot: int) -> bool:
        """Whether ``slot``'s shard exists in the CURRENT clique (drain
        and election-absorb paths; a dead plane keeps covering so its
        landed rows stay drainable)."""
        return slot in self.members

    def quorum_coverable(self, cid) -> bool:
        """Whether the CURRENT clique can reach quorum for ``cid`` (see
        quorum_coverable_for)."""
        return self.quorum_coverable_for(self.members, cid)

    def quorum_coverable_for(self, clique: list[int], cid) -> bool:
        """Whether ``clique`` can own commit for ``cid``: the leader
        must be a clique member (it stages locally) and the clique must
        contain a majority of each active configuration.  Members
        outside the clique still receive entries over the TCP plane
        (the reference replicates to live RC peers the same way)."""
        from apus_tpu.core.cid import CidState
        if self.idx not in clique:
            return False
        old = sum(1 for s in clique if cid.contains(s) and s < cid.size)
        if old < quorum_size(cid.size):
            return False
        if cid.state == CidState.TRANSIT:
            new = sum(1 for s in clique
                      if cid.contains(s) and s < cid.new_size)
            if new < quorum_size(cid.new_size):
                return False
        return True

    # -- re-formation -----------------------------------------------------

    def request_reform(self, epoch: int, members: list[int],
                       svc_addr: Optional[str],
                       term: int) -> Optional[str]:
        """Begin (re)building this process's plane membership for
        ``epoch`` with clique ``members`` (sorted slots).  Returns None
        on acceptance (build proceeds in the background) or a refusal
        reason.  Idempotent for the epoch already being built."""
        del term                        # authenticated by epoch ordering
        members = sorted(members)
        with self.lock:
            if self._stop.is_set():
                return "stopped"
            if self.building:
                return (None if epoch == self._build_target
                        else f"building epoch {self._build_target}")
            if epoch < self.min_epoch:
                return (f"epoch {epoch} < min {self.min_epoch} "
                        f"(incarnation rule)")
            if epoch <= self.epoch:
                return f"epoch {epoch} <= current {self.epoch}"
            if self.idx not in members:
                return f"slot {self.idx} not in clique {members}"
            self.building = True
            self._build_target = epoch
        threading.Thread(
            target=self._build_epoch, args=(epoch, members, svc_addr),
            daemon=True, name=f"apus-mesh-build-{self.idx}-e{epoch}"
        ).start()
        return None

    def _build_epoch(self, epoch: int, members: list[int],
                     svc_addr: Optional[str]) -> None:
        try:
            if svc_addr is None:
                # Epoch-0 bring-up races the coordinator's own launch.
                svc_addr = prepare_epoch(self.spec.mesh_coordinator,
                                         epoch, len(members),
                                         retry_for=30.0)
            self._pre_reform_grace(epoch)
            if self.on_epoch_join is not None:
                self.on_epoch_join(epoch)
            self._log_build(epoch, "teardown")
            self._teardown_jax()
            self._log_build(epoch, "init")

            import jax
            # Rendezvous budget well under mesh_build_timeout: members
            # are told simultaneously, so a healthy clique connects in
            # seconds — a long hang means the fan-out partially failed
            # and the epoch is burned; failing FAST frees this member
            # for the next attempt (compile time is paid after
            # connect and is not under this budget).
            # Rendezvous budget scaled to OVERSUBSCRIPTION: on a box
            # with fewer cores than clique members the teardown +
            # re-init + compile of every member serializes on the same
            # CPUs, so the 1/6th-of-build-timeout floor that is ample
            # on a real pod starves a 1-core CI host into init_timeout
            # churn (each miss burns an epoch).
            try:
                cores = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                cores = os.cpu_count() or 1
            over = max(1, -(-len(members) // max(1, cores)))  # ceil
            init_timeout = min(
                int(self.spec.mesh_build_timeout),
                max(15, int(self.spec.mesh_build_timeout) // 6) * over)
            init_distributed(
                svc_addr, len(members), members.index(self.idx),
                platform=self.spec.mesh_platform,
                init_timeout=init_timeout)
            self._log_build(epoch, "warmup")
            # Import under retry: CPython's import machinery has a rare
            # concurrent-import race (KeyError('apus_tpu.ops') out of
            # _find_and_load_unlocked) when another daemon thread is
            # mid-import of the same package — observed killing an
            # epoch-0 build on a loaded 1-core box.  One short retry
            # heals it (the other thread's import completes).
            for _attempt in (0, 1, 2):
                try:
                    from jax.sharding import (NamedSharding,
                                              PartitionSpec as P)

                    from apus_tpu.ops.commit import \
                        build_pipelined_commit_step
                    from apus_tpu.ops.mesh import (REPLICA_AXIS,
                                                   replica_mesh)
                    break
                except KeyError:
                    if _attempt == 2:
                        raise
                    time.sleep(0.1)

            R = len(members)
            devices = jax.devices()
            if len(devices) < R:
                raise RuntimeError(
                    f"mesh plane needs {R} global devices, "
                    f"have {len(devices)}")
            self._mesh = replica_mesh(R, devices=devices[:R])
            # Shard r must live on process r: the local-shard read path
            # and the leader's local staging both assume it.
            for r, d in enumerate(self._mesh.devices.flat):
                if d.process_index != r:
                    raise RuntimeError(
                        f"mesh device order: shard {r} on process "
                        f"{d.process_index}")
            self._sharding = NamedSharding(self._mesh, P(REPLICA_AXIS))
            self._staged_sharding = NamedSharding(self._mesh,
                                                  P(None, REPLICA_AXIS))
            #: geometry of the arrays being built (self.members still
            #: holds the OLD clique until the swap below) — the array
            #: constructors key on this, never on members.
            self._R = R
            K, B, SB = self.FIXED_WINDOW, self.batch, self.slot_bytes
            # donate=False is LIVENESS here, not a perf choice: shard
            # readers (follower drain, pre-vote drain) materialize
            # host copies concurrently with dispatch.  With donation
            # they must either race a deleted buffer or hold self.lock
            # across an unbounded device sync — which would also wedge
            # _die/quiesce/_do_round (daemon lock) behind a stuck
            # collective, defeating the degrade path.
            # Cost: one extra ring resident transiently per process.
            self._pipe = build_pipelined_commit_step(
                self._mesh, R, self.n_slots, SB, B,
                depth=K, staged_depth=K, verify_round=True,
                donate=False)
            self._jax = jax
            self._np_staged_zero = np.zeros((K, 1, B, SB), np.uint8)
            self._np_meta_zero = np.zeros((K, 1, B, 4), np.int32)
            self._warmup(R)
            q: "queue.Queue" = queue.Queue()
            with self.lock:
                self.members = members
                self.n_replicas = R
                self._row = members.index(self.idx)
                self.epoch = epoch
                self.min_epoch = epoch + 1
                self.generation = 0
                self._worker_gen = 0
                self._term = 0
                self._leader = None
                self._next_end0 = None
                self._seq = 0
                self._expect_seq = 0
                self._devlog = None
                self._outstanding = []
                self._quiesce_since = None
                self._q = q
                self.stats["reforms"] += 1
                self.dead = False
                self.death_reason = None
                self.building = False
                self.ready = True
            threading.Thread(
                target=self._worker_loop, args=(q, epoch), daemon=True,
                name=f"apus-mesh-worker-{self.idx}-e{epoch}").start()
            if self.logger is not None:
                self.logger.info(
                    "mesh plane ready: epoch=%d clique=%s row=%d "
                    "window=%dx%d ring=%d slots", epoch, members,
                    members.index(self.idx), K, B, self.n_slots)
        except Exception as e:                        # noqa: BLE001
            with self.lock:
                self.building = False
                self.min_epoch = max(self.min_epoch, epoch + 1)
            # Log unconditionally (an already-dead plane makes _die a
            # no-op, which would swallow the reason).
            if self.logger is not None:
                self.logger.exception("mesh build epoch %d failed", epoch)
            self._die(f"mesh build epoch {epoch} failed: {e!r}")

    def _log_build(self, epoch: int, phase: str) -> None:
        """Build-phase breadcrumbs: a stuck rebuild (wedged collective
        holding the old backend) is diagnosable only by which phase the
        thread never left."""
        if self.logger is not None:
            self.logger.info("mesh build epoch %d: phase=%s", epoch,
                             phase)

    def _pre_reform_grace(self, epoch: int) -> None:
        """Retire a live plane before teardown: mark it dead (stops
        dispatches, keeps shards readable) and give the driver's drain
        a short grace to absorb landed rows — committed entries are
        safe regardless (they reached the leader's host log before
        dispatch and replicate over TCP); this grace narrows the
        accepted ≤-one-window loss of UNcommitted shard tails (the
        slice-loss failure domain, see _die)."""
        was_alive = False
        with self.lock:
            if not self.dead and self.ready:
                was_alive = True
        if was_alive:
            self._die(f"superseded by re-formation epoch {epoch}")
        # The drain probe reads our shard — a DEVICE SYNC that parks on
        # the producing round.  When the plane died mid-round with the
        # collective WEDGED (feed death with every process alive — no
        # RST to error it out), that sync blocks for gloo's timeout
        # (~60 s), and a build thread stuck here enters the epoch
        # rendezvous a minute after its peers, whose init_timeout then
        # expires: every epoch burns from the skew alone.  Probe from a
        # side thread with a hard answer deadline instead — an
        # unanswered probe means the shard is wedged, and wedged rows
        # are lost with the plane anyway (the ≤-one-window slice-loss
        # failure domain _die accepts).
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and not self._stop.is_set():
            answer: list = []

            def _probe():
                try:
                    answer.append(self._own_drain_pending())
                except Exception:                     # noqa: BLE001
                    answer.append(False)

            t = threading.Thread(target=_probe, daemon=True)
            t.start()
            t.join(timeout=0.75)
            if not answer or not answer[0]:
                return                  # drained, failed, or wedged
            time.sleep(0.05)

    def _own_drain_pending(self) -> bool:
        """Best-effort: does our shard hold rows beyond the host log's
        end (i.e. the driver's drain hasn't caught up)?"""
        from apus_tpu.ops.logplane import OFF_END
        daemon = self._daemon
        with self.lock:
            devlog = self._devlog
        if devlog is None or daemon is None:
            return False
        try:
            row = np.asarray(devlog.offs.addressable_shards[0].data)
            shard_end = int(row[0, OFF_END])
        except Exception:                             # noqa: BLE001
            return False
        with daemon.lock:
            return shard_end > daemon.node.log.end

    def _teardown_jax(self) -> None:
        """Detach from the old epoch: orphan the old worker + queue +
        feeds, drop array/executable refs, tear down the distributed
        client + backend (teardown_distributed).  Non-blocking even
        with a stuck collective (module docstring)."""
        with self.lock:
            self._devlog = None
            old_q = self._q
            self._q = queue.Queue()     # never consumed: parks new items
            feeds = list(self._feeds.values())
            self._feeds.clear()
            self._outstanding = []
            self._pipe = None
            self._mesh = None
            self._sharding = None
            self._staged_sharding = None
        old_q.put(None)
        for f in feeds:
            f.close()
        # First build: nothing to tear down (client is None; the call
        # is a no-op beyond cache clearing).
        teardown_distributed()

    def _warmup(self, R: int) -> None:
        """All processes run the identical warmup (fresh arrays + one
        window) — the first cross-process rendezvous, paying compile
        before any leadership depends on it."""
        devlog = self._fresh_devlog(first_idx=1, leader_row=0, term=0)
        sdata, smeta = self._stage_local(None)
        ctrl = self._ctrl(0, 0, 1, [1] * R, [0] * R,
                          quorum_size(R), 0)
        devlog, commits, _ = self._pipe(devlog, sdata, smeta, ctrl)
        np.asarray(commits)             # block: every process arrived
        # Warm the local-shard read path too (first .addressable_shards
        # readback can trigger a transfer-compile on some backends).
        np.asarray(devlog.offs.addressable_shards[0].data)
        del devlog

    def _fresh_devlog(self, first_idx: int, leader_row: int, term: int):
        from apus_tpu.ops.logplane import make_device_log
        return make_device_log(
            self._R, self.n_slots,
            self.slot_bytes, batch=self.batch, first_idx=first_idx,
            leader=leader_row, term=term, sharding=self._sharding)

    def _stage_local(self, encoded):
        """Build the global staged arrays from THIS process's local
        shard only: the leader passes (data, meta) [K,B,SB]/[K,B,4];
        followers pass None (zeros).  No cross-process communication —
        the in-step pmax moves the payload."""
        jax = self._jax
        K, B, SB = self.FIXED_WINDOW, self.batch, self.slot_bytes
        R = self._R
        if encoded is None:
            ld, lm = self._np_staged_zero, self._np_meta_zero
        else:
            ld = encoded[0].reshape(K, 1, B, SB)
            lm = encoded[1].reshape(K, 1, B, 4)
        data = jax.make_array_from_process_local_data(
            self._staged_sharding, ld, (K, R, B, SB))
        meta = jax.make_array_from_process_local_data(
            self._staged_sharding, lm, (K, R, B, 4))
        return data, meta

    def _ctrl(self, leader_row, term, end0, mask_old, mask_new,
              q_old, q_new):
        import jax.numpy as jnp

        from apus_tpu.ops.commit import CommitControl
        i32 = lambda v: jnp.asarray(v, jnp.int32)     # noqa: E731
        return CommitControl(
            i32(leader_row), i32(term), i32(end0),
            jnp.asarray(np.array(mask_old, np.int32)),
            jnp.asarray(np.array(mask_new, np.int32)),
            i32(q_old), i32(q_new))

    def _die(self, reason: str) -> None:
        """Degrade to TCP: block all DISPATCH paths, but keep the shard
        arrays READABLE.  A follower's pre-vote drain must still be able
        to absorb rows that completed windows landed in its shard —
        discarding them here would let an election proceed without
        entries the dead leader may have acked to clients (they are
        nowhere else yet when the mesh carries the entry transport).
        Reads stay local (no collective), so a live process can always
        attempt them; if the LAST window errored mid-execution its
        buffers are poisoned and the read itself fails — that residual
        (≤ one window of undrained rows lost with the plane) is the
        device plane's shared failure domain, exactly as a TPU slice
        loss takes in-flight HBM state with it.  No longer permanent:
        the reformer rebuilds under the next epoch."""
        with self.lock:
            if self.dead:
                return
            self.dead = True
            self.death_reason = reason
            self._outstanding.clear()
        if self.logger is not None:
            self.logger.error("mesh plane DEAD: %s (TCP plane continues; "
                              "re-formation will follow)", reason)
        for f in self._feeds.values():
            f.close()
        # Fail every caller still parked on a queued round's result —
        # the worker will dispatch nothing further.
        try:
            while True:
                item = self._q.get_nowait()
                if item and item[0] == "round" and item[3] is not None:
                    item[3].put(None)
        except queue.Empty:
            pass

    def _poison_physical(self, reason: str) -> None:
        """Election-budget poison, made PHYSICAL.  ``_die`` alone only
        stops OUR dispatches: the already-dispatched collective keeps
        executing in backend/gloo threads, so a term-T window fed by
        every rank could still complete AFTER the vote below and mint
        a commit through shard acks the election never covered (the
        Raft log-intersection violation ADVICE r5 flagged).  The
        reference closes this race physically — poll_vote_requests
        resets the QPs BEFORE any vote is granted (dare_server.c:1591-
        1652) — and the collective analog is tearing down this rank's
        distributed client + backend: every round is an allreduce over
        ALL clique ranks, so with our gloo transport gone the in-flight
        window can never complete on ANY rank.  The devlog refs go
        with the backend, so up to one window of undrained shard rows
        is lost with the plane — the ≤-one-window slice-loss failure
        domain ``_die`` already accepts; re-formation rebuilds the
        plane under the next epoch."""
        self._die(reason)
        with self.lock:
            if self.building:
                # A newer epoch's build owns the process backend right
                # now (its _teardown_jax already retired the old
                # clique's transport); ripping the backend out from
                # under its init would kill the successor plane.
                return
            self._devlog = None
            self._pipe = None
        try:
            teardown_distributed()
        except Exception:                             # noqa: BLE001
            pass          # best-effort revocation: the plane is dead
                          # either way, and re-formation re-inits

    def _feed_dead(self, addr, exc) -> None:
        self._die(f"descriptor feed to {addr} failed: {exc!r}")

    def _die_if_epoch(self, epoch: int, reason: str) -> None:
        """_die, but only when ``epoch`` is still the live one — a
        STALE worker/handle erroring after a re-formation swapped a
        fresh plane in must not kill the fresh plane."""
        with self.lock:
            if self.epoch != epoch or self.building:
                return
        self._die(reason)

    # -- the single dispatch authority ------------------------------------

    def _worker_loop(self, q: "queue.Queue", epoch: int) -> None:
        """The ONLY thread that dispatches device programs in this
        process — the global program order is the descriptor order,
        identical on every process by construction (rule 2/3).  One
        worker per epoch: a worker whose queue was orphaned by a
        reform exits; one stuck inside a wedged collective is simply
        abandoned (it holds no locks across the dispatch).  Its death
        throes are epoch-guarded so they can never kill a successor
        plane."""
        while not self._stop.is_set():
            item = q.get()
            if item is None or self._q is not q:
                return
            try:
                if item[0] == "reset":
                    self._do_reset(*item[1:])
                else:
                    self._do_round(*item[1:])
            except Exception as e:                    # noqa: BLE001
                self._die_if_epoch(epoch, f"worker dispatch failed: {e!r}")
                if item[0] == "round" and item[3] is not None:
                    item[3].put(None)
                return

    def _do_reset(self, epoch: int, gen: int, leader_slot: int, term: int,
                  first_idx: int) -> None:
        with self.lock:
            if epoch != self.epoch:
                return                  # cross-epoch: defunct stream
            if term < self._term or gen <= self._worker_gen:
                return                  # stale leadership's reset
            try:
                leader_row = self.members.index(leader_slot)
            except ValueError:
                return                  # leader outside our clique
        devlog = self._fresh_devlog(first_idx, leader_row, term)
        with self.lock:
            if epoch != self.epoch:
                return
            self._devlog = devlog
            self._worker_gen = gen
            self.generation = max(self.generation, gen)
            self._leader, self._term = leader_slot, term
            if self.idx != leader_slot:
                # Leader-side _next_end0 was set synchronously in
                # reset() and may already have advanced past first_idx
                # by the time this queue item runs — never clobber it.
                self._next_end0 = first_idx
            self._expect_seq = 0
            self.stats["resets"] += 1
        if self.logger is not None:
            self.logger.info("mesh plane reset: epoch=%d gen=%d leader=%d "
                             "term=%d base=%d", epoch, gen, leader_slot,
                             term, first_idx)

    def _do_round(self, desc: _RoundDesc, encoded, result_q) -> None:
        """Dispatch one window.  ``encoded`` is the leader's staged
        window or None (follower).  ``result_q`` (leader only) receives
        the window handle.  ALWAYS dispatches (rule 3) unless the
        plane is dead or the descriptor is cross-epoch.

        Lock protocol (election safety, module docstring): poisoning
        decision + handle registration happen UNDER the daemon lock;
        the dispatch itself runs OUTSIDE it — it can block for minutes
        inside a wedged collective, and holding the daemon lock there
        would wedge the tick thread (no ticking, no voting, the whole
        replica down with the plane).  The pre-registered handle keeps
        the vote-veto invariant instead."""
        sdata, smeta = self._stage_local(encoded)
        daemon = self._daemon
        dlock = daemon.lock if daemon is not None else threading.RLock()
        with dlock:
            with self.lock:
                if desc.epoch != self.epoch or self._devlog is None:
                    if result_q is not None:
                        result_q.put(None)
                    return
                poisoned = desc.gen != self._worker_gen
                if not poisoned and desc.seq != self._expect_seq:
                    # A gap in the CURRENT generation's stream means a
                    # descriptor was lost: pairing can't be maintained.
                    raise RuntimeError(
                        f"descriptor gap: seq {desc.seq} != "
                        f"{self._expect_seq}")
                if not poisoned:
                    self._expect_seq = desc.seq + 1
            # Term check under the DAEMON lock (election safety): a
            # round below our daemon's current term is poisoned — the
            # in-collective vote fence.
            node_term = (daemon.node.current_term
                         if daemon is not None else desc.term)
            if desc.term < node_term:
                poisoned = True
            if poisoned:
                ctrl = self._ctrl(-3, max(node_term, desc.term) + 1,
                                  desc.end0, desc.mask_old, desc.mask_new,
                                  desc.q_old, desc.q_new)
            else:
                ctrl = self._ctrl(desc.leader, desc.term, desc.end0,
                                  desc.mask_old, desc.mask_new,
                                  desc.q_old, desc.q_new)
            h = MeshWindowHandle(desc.epoch, desc.gen, desc.end0,
                                 self.FIXED_WINDOW, commits=None,
                                 poisoned=poisoned)
            with self.lock:
                self._outstanding.append(h)
        # -- dispatch, DAEMON LOCK RELEASED --
        t0 = time.monotonic()
        # The pipe does NOT donate (see _build_epoch), so the previous
        # devlog's buffers stay valid after dispatch: a shard reader
        # that grabbed self._devlog concurrently reads stale-but-valid
        # data, never a deleted buffer.  (The donating variant killed
        # follower planes under sustained traffic — the drain's
        # shard_end raced one dispatch per ~2k ops and materialized a
        # deleted array; and holding self.lock across
        # dispatch+materialize instead would park _die/quiesce behind
        # a stuck collective.)
        with self.lock:
            devlog = self._devlog
        new_devlog, commits, _ = self._pipe(devlog, sdata, smeta, ctrl)
        h.commits = commits
        with self.lock:
            if desc.epoch == self.epoch:
                self._devlog = new_devlog
        ms = (time.monotonic() - t0) * 1e3
        self.stats["max_dispatch_ms"] = max(
            self.stats.get("max_dispatch_ms", 0.0), ms)
        with self.lock:
            K = self.FIXED_WINDOW
            if poisoned:
                self.stats["poisoned_rounds"] += 1
            else:
                self.stats["rounds"] += K
                self.stats["entries_devplane"] += K * self.batch
                self.stats["pipelined_dispatches"] += 1
                self.depth_histogram[K] = \
                    self.depth_histogram.get(K, 0) + 1
        if result_q is not None:
            result_q.put(h)
        # Follower pacing: bound the dispatched-unresolved pipeline so a
        # backend failure surfaces promptly here (deactivating the
        # plane) instead of silently extending the unresolved chain.
        self._prune_outstanding(limit=4)

    #: How long any blocking wait on a window may take before the plane
    #: is declared dead.  The backend gives NO deadline of its own: a
    #: collective missing one participant blocks until that process
    #: EXITS or gloo times out (probed empirically — up to ~300 s), so
    #: every wait polls is_ready() against this budget instead of
    #: parking forever.  Normal windows complete in milliseconds; the
    #: budget only trips when a descriptor was lost or a peer wedged,
    #: both of which already mean the plane must degrade (and later
    #: re-form).  Sized WELL above worst-case scheduling stalls on an
    #: oversubscribed box (a saturated 1-core host showed 10 s was
    #: trippable by CPU starvation alone, killing healthy planes).
    WAIT_BUDGET_S = 45.0

    def _wait_window(self, h: "MeshWindowHandle", what: str):
        """Readiness-polled wait; returns the commits ndarray or None
        after killing the plane (timeout or collective error).
        ``h.commits`` may still be None for a handle registered but not
        yet dispatched (worker between registration and dispatch) —
        counted as not-ready."""
        deadline = time.monotonic() + self.WAIT_BUDGET_S
        try:
            while h.commits is None or not h.commits.is_ready():
                if time.monotonic() > deadline:
                    self._die_if_epoch(
                        h.epoch, f"{what}: window never completed "
                        f"(missing participant?)")
                    return None
                if self._stop.is_set():
                    return None
                if h.epoch != self.epoch:
                    return None         # superseded by a re-formation
                time.sleep(0.0005)
            return np.asarray(h.commits)
        except Exception as e:                        # noqa: BLE001
            self._die_if_epoch(h.epoch, f"{what} failed: {e!r}")
            return None

    def _prune_outstanding(self, limit: int) -> None:
        while True:
            with self.lock:
                if len(self._outstanding) <= limit:
                    return
                h = self._outstanding[0]
            if self._wait_window(h, "window") is None:
                return
            with self.lock:
                if self._outstanding and self._outstanding[0] is h:
                    self._outstanding.pop(0)

    def quiesce_ready(self) -> bool:
        """Non-blocking pre-vote coverage check (module docstring,
        election safety): True iff every window this process has
        DISPATCHED is executed (its writes are in the shard, ready for
        the pre-vote drain) or the plane is dead (a dead plane's
        unresolved windows never produced a commit anyone adopted).

        Returns False — VOTE VETO — while windows are still executing:
        the election layer defers a tick instead of blocking, so the
        daemon keeps ticking/serving.  The veto is BOUNDED by
        ``spec.mesh_election_budget``: past it the plane is POISONED
        (declared dead — immediate revocation, QP-reset analog,
        dare_ibv_rc.c:2156-2189) and the vote proceeds; re-formation
        restores the plane once the new leadership stabilizes.

        Why the bounded poison is safe: every round is an allreduce
        over ALL clique ranks, so a window whose program has not fed
        our rank's final-round contribution CANNOT complete on any
        rank — no commit can be minted from it, and voting past it
        loses nothing (the common case: our rank starved, or the
        leader's rank died mid-exchange).  The residual exposure is
        the post-contribution EPILOGUE sliver: our rank already fed
        the final reduce (so the leader may resolve and adopt) but our
        local output had not finalized when the budget expired —
        microseconds of device work, stretchable only by a scheduler
        preemption that freezes the backend threadpool while this
        Python thread keeps running.  The budget is sized to dominate
        that sliver with margin (config.py mesh_election_budget); the
        reference closes the same race PHYSICALLY by resetting QPs
        before voting (poll_vote_requests revokes log access,
        dare_server.c:1591-1652), which a dispatched collective has no
        analog for (SURVEY §7 hard parts)."""
        if self.dead:
            return True
        budget = getattr(self.spec, "mesh_election_budget", 0.10)
        with self.lock:
            outstanding = list(self._outstanding)
        for h in outstanding:
            try:
                ready = (h.commits is not None and h.commits.is_ready())
            except Exception as e:                    # noqa: BLE001
                self._die(f"quiesce: window failed: {e!r}")
                return True
            if not ready:
                now = time.monotonic()
                if self._quiesce_since is None:
                    self._quiesce_since = now
                elif now - self._quiesce_since > budget:
                    self._poison_physical(
                        "election pending past the "
                        f"{budget * 1e3:.0f} ms veto budget with "
                        "unresolved windows: plane poisoned "
                        "(re-formation will follow)")
                    return True
                return False
        self._quiesce_since = None
        with self.lock:
            self._outstanding = [h for h in self._outstanding
                                 if h not in outstanding]
        return True

    # -- leader-facing surface (DevicePlaneDriver) ------------------------

    def reset(self, leader: int, term: int,
              first_idx: int) -> Optional[int]:
        """New leadership: fence the descriptor stream + fresh shards on
        every process.  Only meaningful on the leader's process
        (leader == self.idx)."""
        if self.dead or not self.ready:
            return None
        assert leader == self.idx, (leader, self.idx)
        with self.lock:
            if term < self._term or self.idx not in self.members:
                return None
            epoch = self.epoch
            gen = self.generation + 1
            self.generation = gen
            self._term = term
            self._leader = leader
            self._next_end0 = first_idx
            self._seq = 0
        payload = (wire.u8(OP_MESH) + wire.u8(_SUB_RESET)
                   + wire.u64(epoch) + wire.u64(gen)
                   + wire.u8(leader) + wire.u64(term)
                   + wire.u64(first_idx))
        self._broadcast(payload)
        self._q.put(("reset", epoch, gen, leader, term, first_idx))
        if self.dead:
            return None
        return gen

    def _broadcast(self, payload: bytes) -> None:
        for s in self.members:
            if s == self.idx:
                continue
            feed = self._feeds.get(s)
            if feed is None or feed.dead:
                addr = self._peer_addr(s)
                if addr is None:
                    self._die(f"no control endpoint for mesh peer {s}")
                    return
                feed = self._feeds[s] = _PeerFeed(addr, self._feed_dead)
            feed.send(payload)

    def _peer_addr(self, s: int) -> Optional[tuple]:
        peers = self.spec.peers
        if s >= len(peers) or not peers[s]:
            return None
        host, port = peers[s].rsplit(":", 1)
        return host, int(port)

    def commit_rounds_async(self, gen: int, end0: int,
                            entries: list[LogEntry], cid,
                            live: set[int]) -> Optional[MeshWindowHandle]:
        """Stage + describe + dispatch one fixed window without waiting
        for its result (collect via resolve_rounds).  ``entries`` must
        be exactly FIXED_WINDOW * batch, idx-contiguous from end0."""
        if self.dead or not self.ready:
            return None
        K, B, SB = self.FIXED_WINDOW, self.batch, self.slot_bytes
        assert len(entries) == K * B, (len(entries), K, B)
        with self.lock:
            if gen != self.generation:
                return None
            if end0 != self._next_end0:
                return None
            epoch = self.epoch
            members = self.members
            row = self._row
            term = self._term
            seq = self._seq
            self._seq += 1
            self._next_end0 = end0 + K * B
        bd = np.zeros((K, B, SB), np.uint8)
        bm = np.zeros((K, B, 4), np.int32)
        for k in range(K):
            self._encode_batch(entries[k * B:(k + 1) * B], end0 + k * B,
                               bd[k], bm[k])
        from apus_tpu.core.cid import CidState
        # Masks in ROW space over the clique (slot -> row translation;
        # quorum thresholds stay full-configuration sizes — masking
        # shrinks only the numerator, VERDICT-safe coverage is gated by
        # quorum_coverable upstream).
        mask_old = [1 if (cid.contains(s) and s < cid.size) else 0
                    for s in members]
        if cid.state == CidState.TRANSIT:
            mask_new = [1 if (cid.contains(s) and s < cid.new_size) else 0
                        for s in members]
            q_new = quorum_size(cid.new_size)
        else:
            mask_new, q_new = [0] * len(members), 0
        desc = _RoundDesc(epoch, gen, seq, row, term, end0, mask_old,
                          mask_new, quorum_size(cid.size), q_new)
        self._broadcast(desc.encode())
        if self.dead:
            return None
        result_q: "queue.Queue" = queue.Queue(maxsize=1)
        self._q.put(("round", desc, (bd, bm), result_q))
        # Blocks only for the worker's handling of the program (it
        # registers + dispatches promptly), not for execution.
        # Dead-aware wait: if the worker died on an EARLIER queue item,
        # our item may never be serviced (the _die drain and this poll
        # race; either way the caller must not park forever).
        while True:
            try:
                h = result_q.get(timeout=0.5)
                break
            except queue.Empty:
                if self.dead:
                    return None
        if h is not None and h.poisoned:
            return None
        return h

    def _encode_batch(self, entries, end0, out_data, out_meta) -> None:
        SB = self.slot_bytes
        flat = memoryview(out_data.reshape(-1))
        for j, e in enumerate(entries):
            assert e.idx == end0 + j, (e.idx, end0, j)
            size = wire.entry_wire_size(e)
            if size > SB:
                raise ValueError(f"entry {e.idx} wire size {size} > slot "
                                 f"{SB}; segment upstream")
            wire.encode_entry_into(e, flat, j * SB)
            out_meta[j] = (e.req_id & 0x7FFFFFFF, e.clt_id & 0x7FFFFFFF,
                           int(e.type), size)

    def commit_rounds(self, gen: int, end0: int, entries, cid,
                      live) -> Optional[int]:
        h = self.commit_rounds_async(gen, end0, entries, cid, live)
        return None if h is None else self.resolve_rounds(h)

    def commit_round(self, gen, end0, entries, cid, live):
        raise NotImplementedError(
            "mesh plane dispatches fixed windows only (FIXED_WINDOW)")

    def resolve_rounds(self, h: MeshWindowHandle) -> Optional[int]:
        commits_host = self._wait_window(h, "resolve")
        if commits_host is None:
            return None
        B = self.batch
        with self.lock:
            if self._outstanding and h in self._outstanding:
                self._outstanding.remove(h)
            if h.epoch != self.epoch or h.gen != self.generation \
                    or h.poisoned:
                return None
            self.stats["quorum_fail_rounds"] += int(sum(
                int(commits_host[k]) < h.end0 + (k + 1) * B
                for k in range(h.K)))
        return int(commits_host[-1])

    # -- descriptor receive path (PeerServer extra op) --------------------

    def on_descriptor(self, r: wire.Reader) -> bytes:
        """Runs on a PeerServer connection thread (no node lock)."""
        sub = r.u8()
        if sub == _SUB_REFORM:
            epoch = r.u64()
            term = r.u64()
            members = list(r.blob())
            svc_addr = r.blob().decode()
            # Term gate (ADVICE r5 low): a deposed leader that has not
            # yet learned of the higher term must not tear down a
            # healthy plane on every member and rebuild a stale clique
            # — each such cycle costs the whole clique a rendezvous +
            # compile.  Epoch ordering authenticates the BUILD; the
            # daemon's term authenticates the SENDER's right to
            # initiate one.  (term 0 = bootstrap builds, which carry
            # no leadership claim.)
            daemon = self._daemon
            if term > 0 and daemon is not None:
                with daemon.lock:
                    cur = daemon.node.current_term
                if term < cur:
                    reason = (f"REFORM term {term} below current "
                              f"term {cur}: deposed sender")
                    if self.logger is not None:
                        self.logger.warning("REFORM epoch %d refused: %s",
                                            epoch, reason)
                    return (wire.u8(wire.ST_ERROR)
                            + wire.blob(reason.encode()))
            err = self.request_reform(epoch, members, svc_addr, term)
            if err is not None:
                if self.logger is not None:
                    self.logger.warning("REFORM epoch %d refused: %s",
                                        epoch, err)
                return wire.u8(wire.ST_ERROR) + wire.blob(err.encode())
            return wire.u8(wire.ST_OK)
        if sub == _SUB_RESET:
            epoch = r.u64()
            gen = r.u64()
            leader, term, first_idx = r.u8(), r.u64(), r.u64()
        elif sub == _SUB_ROUND:
            desc = _RoundDesc.decode(r)
            epoch = desc.epoch
        else:
            return wire.u8(wire.ST_ERROR)
        if not self._await_epoch(epoch):
            # Cross-epoch or dead: NACK — the sender's feed dies, its
            # plane degrades, re-formation reconciles (module
            # docstring rule 3, across-epochs case).
            return wire.u8(wire.ST_ERROR)
        if sub == _SUB_RESET:
            self._q.put(("reset", epoch, gen, leader, term, first_idx))
        else:
            self._q.put(("round", desc, None, None))
        return wire.u8(wire.ST_OK)

    def _await_epoch(self, epoch: int) -> bool:
        """Descriptors can only flow once every process passed the
        warmup RENDEZVOUS — so a descriptor for an epoch we haven't
        finished building means our build thread is in its last
        moments of bookkeeping while a faster peer's already
        dispatched.  Wait it out briefly (a nack would kill the whole
        plane over a thread-scheduling race); a build that really
        failed flips ``dead``/bumps min_epoch."""
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not self._stop.is_set():
            with self.lock:
                if self.ready and not self.dead and self.epoch == epoch:
                    return True
                if self.epoch > epoch or epoch < self.min_epoch:
                    return False        # stale stream: NACK now
                if not self.building and (self.dead or not self.ready):
                    return False
            time.sleep(0.005)
        return False

    # -- local shard readback ---------------------------------------------

    def _local_shard(self, arr):
        shards = arr.addressable_shards
        assert len(shards) == 1, len(shards)
        return shards[0].data            # [1, ...] on our device

    def shard_end(self, replica: int, gen: int) -> Optional[int]:
        """Reads stay LOCAL and remain available even when the plane is
        dead — the follower drain (and the pre-vote drain) must still
        absorb rows completed windows landed in our shard (see _die)."""
        from apus_tpu.ops.logplane import OFF_END
        if replica != self.idx:
            return None                 # only our own shard is local
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            offs = self._devlog.offs
        # Materialize OUTSIDE the lock: the pipe does not donate (see
        # _build_epoch), so this reference stays valid even if a new
        # round dispatches+swaps concurrently; the sync here parks only
        # THIS reader until the producing round completes.
        try:
            row = np.asarray(self._local_shard(offs))
        except Exception as e:                        # noqa: BLE001
            self._die(f"shard read failed: {e!r}")
            return None
        return int(row[0, OFF_END])

    def read_rows(self, replica: int, gen: int, lo: int, hi: int,
                  window: bool = False) -> Optional[list[LogEntry]]:
        from apus_tpu.ops.logplane import META_IDX, META_LEN, slot_of
        if replica != self.idx:
            return None
        cap = self.batch * (self.FIXED_WINDOW if window else 1)
        hi = min(hi, lo + cap)
        slots = slot_of(lo + np.arange(hi - lo, dtype=np.int64),
                        self.n_slots).astype(np.int32)
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            if hi <= lo:
                return []
            data_arr, meta_arr = self._devlog.data, self._devlog.meta
        # Bulk copy OUTSIDE the lock — non-donated buffers stay valid
        # (see shard_end); holding self.lock across a whole-shard
        # device sync would serialize _do_round (which waits on it)
        # behind every drain.
        try:
            data = np.asarray(self._local_shard(data_arr))[0][slots]
            meta = np.asarray(self._local_shard(meta_arr))[0][slots]
        except Exception as e:                        # noqa: BLE001
            self._die(f"shard read failed: {e!r}")
            return None
        out: list[LogEntry] = []
        for j, idx in enumerate(range(lo, hi)):
            if int(meta[j, META_IDX]) != idx:
                break
            n = int(meta[j, META_LEN])
            blob = data[j, :n].tobytes()
            try:
                e = wire.decode_entry(wire.Reader(blob))
            except Exception:                         # noqa: BLE001
                break
            if e.idx != idx:
                break
            out.append(e)
        return out


# -- reformer ---------------------------------------------------------------


def _send_reform(addr: str, payload: bytes,
                 timeout: float = 5.0) -> Optional[str]:
    """One-shot REFORM send to a peer's PeerServer.  Returns None on
    ST_OK, else a reason string."""
    host, port = addr.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as s:
            s.settimeout(timeout)
            s.sendall(wire.frame(payload))
            resp = wire.read_frame(s)
    except OSError as e:
        return f"unreachable: {e}"
    if resp is None:
        return "hung up"
    if resp[:1] != bytes([wire.ST_OK]):
        try:
            return wire.Reader(resp[1:]).blob().decode()
        except Exception:                             # noqa: BLE001
            return "refused"
    return None


class MeshReformer:
    """Leader-side re-formation orchestrator (one thread per daemon,
    active only while this daemon leads).

    The reference analog: the leader re-establishes its RC data plane
    to a returning server (RC_SYN/SYNACK/ACK re-handshake,
    dare_ibv_ud.c:1098-1416; QPs re-granted dare_ibv_rc.c:2195-2255).
    Here the whole clique re-rendezvouses under a fresh epoch, because
    a gloo/ICI clique — like a TPU slice — is rebuilt as a unit.

    Trigger: this daemon is leader, the target clique (live mesh-
    capable members) could own quorum, the clique has been STABLE for
    ``spec.mesh_reform_stable`` seconds, and the local plane is not
    healthy-for-this-clique.  All clique members must be reachable and
    not mid-build; the next epoch is one past the maximum epoch any of
    them ever joined (incarnation rule).  The coordination service is
    PREPAREd first, then REFORM fans out over the TCP control plane;
    the build outcome is awaited (bounded by spec.mesh_build_timeout)
    before another attempt — a failed attempt burns its epoch and
    retries with the next."""

    def __init__(self, daemon, runner: MeshCommitRunner, spec):
        self.daemon = daemon
        self.runner = runner
        self.spec = spec
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stable_key = None
        self._stable_since = 0.0
        #: highest epoch the coordinator REFUSED to PREPARE (a crashed
        #: leader's half-joined service instance of another size sits
        #: there) — proposals must skip past it or the scan recomputes
        #: the same refused epoch forever (ADVICE r5 livelock).
        self._burned_epoch = -1
        #: Adaptive retry backoff: consecutive FAILED re-formations
        #: double the pause before the next attempt (capped below).  A
        #: fixed 0.25 s scan cadence burned one epoch every ~2.5 s when
        #: builds failed deterministically — on a starved 1-core box
        #: the storm of teardown+re-init cycles itself kept the builds
        #: failing (the 2 residual tier-1 failures rode this).  Success
        #: resets the backoff.
        self._consec_failures = 0
        self._backoff_until = 0.0
        self.stats = {"reforms_started": 0, "reforms_ok": 0,
                      "reforms_failed": 0, "epochs_burned": 0}

    def start(self) -> None:
        if not getattr(self.spec, "mesh_reform", True):
            return
        t = threading.Thread(target=self._run, daemon=True,
                             name=f"apus-mesh-reform-{self.daemon.idx}")
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._scan()
            except Exception:                         # noqa: BLE001
                if self.daemon.logger is not None:
                    self.daemon.logger.exception("mesh reformer scan")
            self._stop.wait(0.25)

    def _target_clique(self) -> Optional[tuple[list[int], int]]:
        """(clique, term) when this daemon leads and the clique could
        own quorum; None otherwise."""
        node = self.daemon.node
        with self.daemon.lock:
            if not node.is_leader:
                return None
            term = node.current_term
            cid = node.cid
            members = sorted(cid.members())
        spec = self.spec
        clique = [s for s in members
                  if s < spec.mesh_n and s < len(spec.peers)
                  and spec.peers[s]]
        if self.daemon.idx not in clique:
            return None
        with self.daemon.lock:
            if not self.runner.quorum_coverable_for(clique,
                                                    self.daemon.node.cid):
                return None
        return clique, term

    def _acquire_epoch(self, next_epoch: int,
                       n: int) -> Optional[tuple[int, str]]:
        """PREPARE ``next_epoch`` for an ``n``-process clique at the
        coordinator, treating a REFUSED epoch as burned: a leader that
        crashed between its own PREPARE(E, n') and the REFORM fan-out
        leaves a half-joined service instance at E that can never
        change size, so the coordinator refuses PREPARE(E, n) forever.
        Pre-fix the scan recomputed the same E every pass and
        re-formation livelocked (plane stuck TCP-only) until the clique
        happened to regain size n'; now each refusal records the burned
        epoch and retries with the next one (bounded per scan).
        Returns (epoch, service_addr) or None (transport failure, or
        every attempt refused — the next scan resumes past the burn
        mark)."""
        for _ in range(8):
            try:
                svc = prepare_epoch(self.spec.mesh_coordinator,
                                    next_epoch, n)
                return next_epoch, svc
            except RuntimeError:
                # ST_ERROR from the coordinator: refusal, not outage.
                self._burned_epoch = max(self._burned_epoch, next_epoch)
                self.stats["epochs_burned"] += 1
                self.daemon.logger.warning(
                    "mesh reform: epoch %d burned (half-joined service "
                    "instance of another size); retrying with %d",
                    next_epoch, next_epoch + 1)
                next_epoch += 1
            except Exception as e:                    # noqa: BLE001
                self.daemon.logger.warning(
                    "mesh reform: coordinator PREPARE(%d) failed: %s",
                    next_epoch, e)
                return None
        return None

    def _fail_backoff(self) -> None:
        """Record a failed attempt and schedule the next one with
        exponential backoff (base = the stability window, capped)."""
        self.stats["reforms_failed"] += 1
        self._consec_failures += 1
        base = getattr(self.spec, "mesh_reform_stable", 2.0)
        pause = min(30.0, base * (2 ** min(self._consec_failures, 6)))
        self._backoff_until = time.monotonic() + pause
        self.daemon.logger.warning(
            "mesh reform: attempt %d failed; backing off %.1f s",
            self._consec_failures, pause)

    def _scan(self) -> None:
        from apus_tpu.runtime.client import probe_status
        runner = self.runner
        if time.monotonic() < self._backoff_until:
            return
        tc = self._target_clique()
        if tc is None:
            self._stable_key = None
            return
        clique, term = tc
        if runner.building:
            return
        healthy = (runner.ready and not runner.dead
                   and runner.members == clique)
        if healthy:
            self._stable_key = None
            return
        # Stability window: the clique+term must hold unchanged for
        # mesh_reform_stable before acting (no reforming mid-churn).
        key = (term, tuple(clique))
        now = time.monotonic()
        if key != self._stable_key:
            self._stable_key = key
            self._stable_since = now
            return
        if now - self._stable_since < getattr(self.spec,
                                              "mesh_reform_stable", 2.0):
            return
        # Collect member plane states: all reachable, none mid-build.
        # A member that answers status but has NO device plane at all
        # (--no-device-plane operator choice) is structurally TCP-only:
        # drop it from the clique rather than blocking re-formation
        # forever — but a probe FAILURE is a transient, retried later.
        last_epochs = [runner.epoch]
        tcp_only = []
        for s in clique:
            if s == self.daemon.idx:
                continue
            st = probe_status(self.spec.peers[s], timeout=1.0)
            if st is None:
                return
            dp = st.get("devplane")
            if dp is None:
                tcp_only.append(s)
                continue
            if dp.get("building"):
                return
            ep = dp.get("epoch")
            last_epochs.append(-1 if ep is None else ep)
            # An epoch someone STARTED building (even if it failed or
            # is in flight elsewhere) is burned for proposals too.
            bt = dp.get("build_target")
            if bt is not None:
                last_epochs.append(bt)
        if tcp_only:
            clique = [s for s in clique if s not in tcp_only]
            with self.daemon.lock:
                coverable = runner.quorum_coverable_for(
                    clique, self.daemon.node.cid)
            if not coverable:
                return
        next_epoch = max(max(last_epochs), runner.min_epoch - 1,
                         self._burned_epoch) + 1
        acquired = self._acquire_epoch(next_epoch, len(clique))
        if acquired is None:
            return
        next_epoch, svc = acquired
        self.daemon.logger.info(
            "mesh reform: epoch %d clique=%s svc=%s", next_epoch,
            clique, svc)
        self.stats["reforms_started"] += 1
        payload = encode_reform(next_epoch, clique, svc, term)
        local_err = None
        for s in clique:
            if s == self.daemon.idx:
                err = local_err = runner.request_reform(
                    next_epoch, clique, svc, term)
            else:
                err = _send_reform(self.spec.peers[s], payload)
            if err is not None:
                # The epoch is burned (some members may already be
                # building it); their builds fail at init_timeout and
                # the next scan retries with a fresh epoch.
                self.daemon.logger.warning(
                    "mesh reform: member %d refused epoch %d: %s",
                    s, next_epoch, err)
        if local_err is not None:
            # Without a local build there is no outcome to await —
            # re-evaluate on the next scan instead of idling here.
            self._fail_backoff()
            self._stable_key = None
            return
        # Await OUR build outcome (bounded); member readiness is
        # observable via status and gates the driver naturally.
        deadline = now + getattr(self.spec, "mesh_build_timeout", 120.0)
        while not self._stop.is_set() and time.monotonic() < deadline:
            if runner.ready and not runner.dead \
                    and runner.epoch == next_epoch:
                self.stats["reforms_ok"] += 1
                self._consec_failures = 0
                self._backoff_until = 0.0
                self.daemon.logger.info(
                    "mesh reform: epoch %d LIVE (clique %s)",
                    next_epoch, clique)
                return
            if not runner.building and runner.min_epoch > next_epoch \
                    and runner.epoch != next_epoch:
                break                   # build failed; epoch burned
            self._stop.wait(0.25)
        self._fail_backoff()
        self._stable_key = None         # restart the stability window


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.runtime.mesh_plane",
        description="Host the mesh-plane coordination control server "
                    "(one per cluster, outside every replica).")
    ap.add_argument("--serve-coordinator", required=True, metavar="ADDR",
                    help="host:port to bind the control server on")
    ap.add_argument("--n", type=int, required=False, default=0,
                    help="advisory process count (sizes arrive per "
                         "epoch in PREPARE)")
    a = ap.parse_args()
    serve_coordinator(a.serve_coordinator, a.n)
