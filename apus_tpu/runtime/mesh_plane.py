"""Multi-controller device plane: process-per-replica commit over a
global ``jax.distributed`` mesh.

The reference's one-sided data plane runs INSIDE every server process —
each machine's DARE thread posts RDMA writes from its own address space
(``rc_write_remote_logs`` called from the server's commit loop,
dare_ibv_rc.c:1870-1948).  The in-process ``DeviceCommitRunner``
(runtime.device_plane) gives that shape to daemons sharing ONE process;
THIS module gives it to the production deployment: one OS process per
replica (runtime.proc / runtime.daemon), each owning one device of a
global ``jax.sharding.Mesh`` glued together by ``jax.distributed`` —
exactly how a multi-host TPU pod runs one JAX program per host.

How a round works (multi-controller SPMD):

- Every process dispatches the SAME compiled program (the pipelined
  commit step of ops.commit with ``verify_round=True``).  The leader's
  process stages its window into ITS local input shard; followers stage
  zeros.  The in-step ``pmax`` broadcast then moves the batch
  device-to-device over the interconnect — followers' HOST code never
  touches the payload, which is precisely the reference's one-sided
  write semantics (followers passive on the replication path).
- Followers learn WHAT to dispatch from a round DESCRIPTOR the leader
  sends over the TCP control plane (a PeerServer extra op, OP_MESH) —
  control metadata (term, end0, masks), never entry payload.  This
  mirrors the reference's UD-control/RC-data split.
- Each process reads results from its OWN addressable shard — no
  collective on the read path (the rc_recover_log analog of reading
  back the memory the RDMA writes landed in).

Global program order (the multi-controller invariant): the backend
pairs collectives across processes by dispatch order, so every process
must issue the identical sequence of identical-shaped programs.  Three
rules enforce it:

1. ONE window shape.  Every dispatch is ``spec.mesh_depth`` rounds of
   one batch (partial backlog is NOOP-padded by the driver), so
   mismatched-shape pairings are structurally impossible.
2. ONE dispatch authority per process — the worker thread — consuming
   an ordered queue fed locally (leader) and by descriptor arrivals
   (followers).
3. NEVER drop, always POISON.  A descriptor that is stale (old
   generation, or a term below the daemon's current term) is still
   dispatched — pairing! — but with a poisoned round identity, so the
   in-step ``verify_round`` check refuses the write EVERYWHERE and the
   round decides nothing.  This is the in-step form of QP-reset
   fencing (dare_ibv_rc.c:2156-2255): the deposed leader's write
   executes against the fabric but cannot land or mint a commit.

Election safety (why device acks may count toward commit at all): a
follower's vote must cover every entry its shard ever acked, or a
deposed leader could commit through shard acks the new leader's
election never saw.  Two mechanisms close this:

- The worker dispatches UNDER THE DAEMON LOCK with a term check — any
  round at a term below the daemon's is poisoned (a voter that moved
  to term T+1 refuses T-rounds *in the collective itself*).
- ``quiesce_ready()`` — consulted by the driver's pre-election hook
  before ANY vote is granted or campaign starts.  While a window this
  process dispatched is still executing, the vote is VETOED (deferred
  a tick — never blocked in place, which would wedge the daemon while
  e.g. a dead leader's half-dispatched collective takes seconds to
  error out); once all windows are executed, the shard drain absorbs
  the landed rows into the host log and the vote proceeds.  Every
  round is therefore either (a) executed + drained before the vote
  (counted in the vote's log-up-to-dateness, standard Raft
  intersection), or (b) dispatched after it, hence poisoned by the
  term check.  Windows merely QUEUED at hook time dispatch after the
  vote, i.e. (b).  Liveness cost: after a leader dies with windows in
  flight, elections wait for the backend to surface the error (~1-5 s
  observed) — the same order as the reference waiting out RDMA retry
  exhaustion before a QP error frees its voters.

Failure semantics (the ICI-slice model): the distributed runtime is
brought up with effectively-infinite coordination heartbeats — the
default behavior (terminating every process ~100 s after one dies;
probed empirically on jaxlib 0.9) would turn a single replica crash
into a total outage.  Member death is detected the way the data plane
itself sees it: the collective errors out promptly and CATCHABLY
(connection reset), the worker deactivates the plane, and the daemon
continues on the TCP plane — the reference degrades the same way when
a NIC dies and its QPs error out (WC error taxonomy,
dare_ibv_rc.c:3202-3314).  A degraded mesh plane stays down until the
cluster restarts (a TPU slice behaves the same way); consensus never
depends on it.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import socket
import threading
from typing import Optional

import numpy as np

from apus_tpu.core.log import LogEntry
from apus_tpu.core.quorum import quorum_size
from apus_tpu.parallel import wire

#: PeerServer extra-op for mesh-plane descriptors (leader -> follower).
OP_MESH = 13
_SUB_RESET = 0
_SUB_ROUND = 1

#: Effectively-infinite coordination heartbeat (seconds): liveness is
#: the consensus layer's job; the device plane learns of death from
#: collective errors (see module docstring).
_NO_HEARTBEAT = 10 ** 7


def serve_coordinator(addr: str, n_processes: int) -> None:
    """Host the jax.distributed coordination service and nothing else.

    The service lives in its OWN process, outside every replica: a
    replica that hosted it would couple the whole mesh's fate to its
    own — the runtime's error-polling treats "coordination service
    unreachable" as LOG(FATAL) and terminates every member (observed
    empirically), turning one replica crash into a total outage.  A
    dedicated coordinator is never a fault-injection target, exactly
    like the reference's IB subnet manager is not one of the replicas.
    Blocks forever (run it under a supervisor)."""
    from jax._src.lib import _jax
    svc = _jax.get_distributed_runtime_service(
        addr, n_processes,
        heartbeat_timeout=_NO_HEARTBEAT, shutdown_timeout=5)
    import time as _time
    print(f"APUS-MESH-COORDINATOR ready at {addr} for {n_processes} "
          f"processes", flush=True)
    # Orphan watchdog (same contract as the replica daemon's, see
    # daemon.py main loop): the env var carries the HARNESS pid; when
    # our parent is no longer that pid the harness died without
    # stop() — exit instead of serving a dead mesh forever.
    try:
        harness_pid = int(os.environ.get("APUS_EXIT_IF_ORPHANED", ""))
    except ValueError:
        harness_pid = 0
    try:
        while True:
            if harness_pid > 0 and os.getppid() != harness_pid:
                print("harness gone; coordinator exiting "
                      "(APUS_EXIT_IF_ORPHANED)", flush=True)
                return
            _time.sleep(2.0)
    finally:
        del svc


def init_distributed(coordinator: str, n_processes: int, process_id: int,
                     platform: str = "cpu",
                     init_timeout: int = 120,
                     host_service: bool = False) -> None:
    """Bring up ``jax.distributed`` with consensus-friendly failure
    semantics (no heartbeat-triggered process termination, no exit-time
    shutdown barrier).  Must run before the first jax backend
    initialization in this process.  ``platform='cpu'`` pins the CPU
    backend (gloo collectives) for CPU deployments/tests; '' leaves the
    platform alone (real TPU pods).  ``host_service`` embeds the
    coordination service in process 0 — ONLY for hermetic harnesses
    (dryrun); deployments run ``serve_coordinator`` in its own process
    (see its docstring for why)."""
    import os

    import jax

    if platform:
        os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
        # Exactly ONE local device per process: shard r must live on
        # process r.  A virtual multi-device flag inherited from a test
        # environment (xla_force_host_platform_device_count) would give
        # every process N local devices and put the whole mesh's first
        # N shards on process 0.
        flags = os.environ.get("XLA_FLAGS", "")
        scrubbed = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f)
        if scrubbed != flags:
            os.environ["XLA_FLAGS"] = scrubbed
        try:
            jax.config.update("jax_platforms", platform)
            if platform == "cpu":
                jax.config.update("jax_num_cpu_devices", 1)
        except RuntimeError:
            pass                        # backend already up: caller's bed
    from jax._src import distributed
    from jax._src.lib import _jax

    state = distributed.global_state
    if state.client is not None:
        return                          # already initialized
    if host_service and process_id == 0:
        state.service = _jax.get_distributed_runtime_service(
            coordinator, n_processes,
            heartbeat_timeout=_NO_HEARTBEAT, shutdown_timeout=5)
    state.client = _jax.get_distributed_runtime_client(
        coordinator, process_id, init_timeout=init_timeout,
        heartbeat_timeout=_NO_HEARTBEAT, shutdown_on_destruction=False,
        use_compression=True)
    state.client.connect()
    state.process_id = process_id
    state.num_processes = n_processes
    state.coordinator_address = coordinator


@dataclasses.dataclass
class _RoundDesc:
    """Everything a follower needs to dispatch the identical program."""

    gen: int
    seq: int
    leader: int
    term: int
    end0: int
    mask_old: list
    mask_new: list
    q_old: int
    q_new: int

    def encode(self) -> bytes:
        return (wire.u8(OP_MESH) + wire.u8(_SUB_ROUND)
                + wire.u64(self.gen) + wire.u64(self.seq)
                + wire.u8(self.leader) + wire.u64(self.term)
                + wire.u64(self.end0) + wire.u8(self.q_old)
                + wire.u8(self.q_new)
                + wire.blob(bytes(self.mask_old))
                + wire.blob(bytes(self.mask_new)))

    @staticmethod
    def decode(r: wire.Reader) -> "_RoundDesc":
        gen, seq = r.u64(), r.u64()
        leader, term, end0 = r.u8(), r.u64(), r.u64()
        q_old, q_new = r.u8(), r.u8()
        mask_old = list(r.blob())
        mask_new = list(r.blob())
        return _RoundDesc(gen, seq, leader, term, end0,
                          mask_old, mask_new, q_old, q_new)


class _PeerFeed:
    """Per-peer FIFO descriptor sender: one dedicated TCP connection to
    the peer's PeerServer, one thread draining a queue of frames.  Any
    send/ack failure marks the feed dead and trips the runner's
    deactivation — a follower that misses one descriptor can never
    rejoin the dispatch sequence (module docstring rule 3 covers
    orderings, not losses)."""

    def __init__(self, addr: tuple, on_dead, timeout: float = 2.0):
        self.addr = addr
        self.on_dead = on_dead
        self.timeout = timeout
        self.q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self.dead = False
        self._sock: Optional[socket.socket] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def send(self, payload: bytes) -> None:
        if not self.dead:
            self.q.put(payload)

    def close(self) -> None:
        self.q.put(None)

    def _run(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                break
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        self.addr, timeout=self.timeout)
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                    self._sock.settimeout(self.timeout)
                self._sock.sendall(wire.frame(item))
                resp = wire.read_frame(self._sock)
                if resp is None or resp[:1] != bytes([wire.ST_OK]):
                    raise ConnectionError(f"mesh feed nack {resp!r}")
            except Exception as e:                    # noqa: BLE001
                self.dead = True
                self.on_dead(self.addr, e)
                break
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass


class MeshWindowHandle:
    """In-flight window handle (device-side commits vector + the
    expectations to account for it at resolve time)."""

    __slots__ = ("gen", "end0", "K", "commits", "poisoned")

    def __init__(self, gen: int, end0: int, K: int, commits,
                 poisoned: bool = False):
        self.gen, self.end0, self.K = gen, end0, K
        self.commits, self.poisoned = commits, poisoned


class MeshCommitRunner:
    """Driver-facing runner whose shards live one-per-process on a
    global mesh.  Exposes the DeviceCommitRunner surface the
    DevicePlaneDriver consumes, plus ``FIXED_WINDOW`` (the single
    window shape every dispatch uses)."""

    WIRE_OVERHEAD = 64

    def __init__(self, spec, idx: int, logger=None):
        self.spec = spec
        self.idx = idx
        self.logger = logger
        self.n_replicas = spec.mesh_n
        self.batch = spec.max_batch
        K = spec.mesh_depth
        self.FIXED_WINDOW = K
        # Driver compatibility: every rung IS the fixed window.
        self.PIPE_DEPTH = K
        self.DEEP_DEPTH = K
        self.window_depths = [K]
        self.use_async_windows = True
        self.slot_bytes = spec.mesh_slot_bytes
        # Ring sized for the deployable async shape by default:
        # MAX_INFLIGHT windows in flight plus one staging must fit
        # ((inflight+K)*B <= S, the driver's capacity gate).
        self.n_slots = spec.mesh_slots or 4 * K * self.batch
        self.lock = threading.Lock()
        self.generation = 0
        self._worker_gen = 0            # generation of the worker's arrays
        self._term = 0
        self._leader: Optional[int] = None
        self._next_end0: Optional[int] = None
        self._seq = 0                   # leader-side descriptor ordinal
        self._expect_seq = 0            # follower-side ordinal (per gen)
        self.stats = {"rounds": 0, "resets": 0, "quorum_fail_rounds": 0,
                      "entries_devplane": 0, "pipelined_dispatches": 0,
                      "poisoned_rounds": 0}
        self.depth_histogram: dict[int, int] = {}
        self.pallas_modes: dict[int, Optional[str]] = {K: None}
        self.ready = False
        self.dead = False
        self.death_reason: Optional[str] = None
        self._devlog = None
        self._q: "queue.Queue" = queue.Queue()
        #: every dispatched-but-unresolved window (leader AND follower
        #: sides) — quiesce_ready() gates votes on all of them.
        self._outstanding: list[MeshWindowHandle] = []
        self._quiesce_since = None      # unready-window stopwatch
        self._feeds: dict[int, _PeerFeed] = {}
        self._daemon = None             # attach() target
        self._stop = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def attach(self, daemon) -> None:
        """Bind the (single) local daemon: the worker's term checks and
        dispatch ordering are serialized through its lock."""
        self._daemon = daemon

    def start(self) -> None:
        """Kick off the (blocking, collective) distributed bring-up in
        the background; the daemon serves TCP consensus immediately and
        the driver engages once ``ready``."""
        t = threading.Thread(target=self._build, daemon=True,
                             name=f"apus-mesh-build-{self.idx}")
        t.start()

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)
        for f in self._feeds.values():
            f.close()

    def max_data_bytes(self) -> int:
        return self.slot_bytes - self.WIRE_OVERHEAD

    # -- build (background thread; rendezvous with every process) ---------

    def _build(self) -> None:
        try:
            import jax

            init_distributed(self.spec.mesh_coordinator, self.n_replicas,
                             self.idx, platform=self.spec.mesh_platform)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from apus_tpu.ops.commit import build_pipelined_commit_step
            from apus_tpu.ops.mesh import REPLICA_AXIS, replica_mesh

            devices = jax.devices()
            if len(devices) < self.n_replicas:
                raise RuntimeError(
                    f"mesh plane needs {self.n_replicas} global devices, "
                    f"have {len(devices)}")
            self._mesh = replica_mesh(self.n_replicas,
                                      devices=devices[:self.n_replicas])
            # Shard r must live on process r: the local-shard read path
            # and the leader's local staging both assume it.
            for r, d in enumerate(self._mesh.devices.flat):
                if d.process_index != r:
                    raise RuntimeError(
                        f"mesh device order: shard {r} on process "
                        f"{d.process_index}")
            self._sharding = NamedSharding(self._mesh, P(REPLICA_AXIS))
            self._staged_sharding = NamedSharding(self._mesh,
                                                  P(None, REPLICA_AXIS))
            K, B, SB = self.FIXED_WINDOW, self.batch, self.slot_bytes
            # donate=False is LIVENESS here, not a perf choice: shard
            # readers (follower drain, pre-vote drain) materialize
            # host copies concurrently with dispatch.  With donation
            # they must either race a deleted buffer or hold self.lock
            # across an unbounded device sync — which would also wedge
            # _die/quiesce/_do_round (daemon lock) behind a stuck
            # collective, defeating the WAIT_BUDGET_S degrade path.
            # Cost: one extra ring resident transiently per process.
            self._pipe = build_pipelined_commit_step(
                self._mesh, self.n_replicas, self.n_slots, SB, B,
                depth=K, staged_depth=K, verify_round=True,
                donate=False)
            self._jax = jax
            self._np_staged_zero = np.zeros((K, 1, B, SB), np.uint8)
            self._np_meta_zero = np.zeros((K, 1, B, 4), np.int32)
            self._warmup()
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"apus-mesh-worker-{self.idx}").start()
            self.ready = True
            if self.logger is not None:
                self.logger.info(
                    "mesh plane ready: %d processes, window=%dx%d, "
                    "ring=%d slots", self.n_replicas, K, B, self.n_slots)
        except Exception as e:                        # noqa: BLE001
            self._die(f"mesh build failed: {e!r}")

    def _warmup(self) -> None:
        """All processes run the identical warmup (fresh arrays + one
        window) — the first cross-process rendezvous, paying compile
        before any leadership depends on it."""
        devlog = self._fresh_devlog(first_idx=1, leader=0, term=0)
        sdata, smeta = self._stage_local(None)
        ctrl = self._ctrl(0, 0, 1, [1] * self.n_replicas,
                          [0] * self.n_replicas,
                          quorum_size(self.n_replicas), 0)
        devlog, commits, _ = self._pipe(devlog, sdata, smeta, ctrl)
        np.asarray(commits)             # block: every process arrived
        # Warm the local-shard read path too (first .addressable_shards
        # readback can trigger a transfer-compile on some backends).
        np.asarray(devlog.offs.addressable_shards[0].data)
        del devlog

    def _fresh_devlog(self, first_idx: int, leader: int, term: int):
        from apus_tpu.ops.logplane import make_device_log
        return make_device_log(
            self.n_replicas, self.n_slots, self.slot_bytes,
            batch=self.batch, first_idx=first_idx, leader=leader,
            term=term, sharding=self._sharding)

    def _stage_local(self, encoded):
        """Build the global staged arrays from THIS process's local
        shard only: the leader passes (data, meta) [K,B,SB]/[K,B,4];
        followers pass None (zeros).  No cross-process communication —
        the in-step pmax moves the payload."""
        jax = self._jax
        K, B, SB = self.FIXED_WINDOW, self.batch, self.slot_bytes
        R = self.n_replicas
        if encoded is None:
            ld, lm = self._np_staged_zero, self._np_meta_zero
        else:
            ld = encoded[0].reshape(K, 1, B, SB)
            lm = encoded[1].reshape(K, 1, B, 4)
        data = jax.make_array_from_process_local_data(
            self._staged_sharding, ld, (K, R, B, SB))
        meta = jax.make_array_from_process_local_data(
            self._staged_sharding, lm, (K, R, B, 4))
        return data, meta

    def _ctrl(self, leader, term, end0, mask_old, mask_new, q_old, q_new):
        import jax.numpy as jnp

        from apus_tpu.ops.commit import CommitControl
        i32 = lambda v: jnp.asarray(v, jnp.int32)     # noqa: E731
        return CommitControl(
            i32(leader), i32(term), i32(end0),
            jnp.asarray(np.array(mask_old, np.int32)),
            jnp.asarray(np.array(mask_new, np.int32)),
            i32(q_old), i32(q_new))

    def _die(self, reason: str) -> None:
        """Degrade to TCP: block all DISPATCH paths, but keep the shard
        arrays READABLE.  A follower's pre-vote drain must still be able
        to absorb rows that completed windows landed in its shard —
        discarding them here would let an election proceed without
        entries the dead leader may have acked to clients (they are
        nowhere else yet when the mesh carries the entry transport).
        Reads stay local (no collective), so a live process can always
        attempt them; if the LAST window errored mid-execution its
        donated buffers are poisoned and the read itself fails — that
        residual (≤ one window of undrained rows lost with the plane)
        is the device plane's shared failure domain, exactly as a TPU
        slice loss takes in-flight HBM state with it."""
        with self.lock:
            if self.dead:
                return
            self.dead = True
            self.death_reason = reason
            self._outstanding.clear()
        if self.logger is not None:
            self.logger.error("mesh plane DEAD: %s (TCP plane continues)",
                              reason)
        for f in self._feeds.values():
            f.close()
        # Fail every caller still parked on a queued round's result —
        # the worker will dispatch nothing further.
        try:
            while True:
                item = self._q.get_nowait()
                if item and item[0] == "round" and item[3] is not None:
                    item[3].put(None)
        except queue.Empty:
            pass

    def _feed_dead(self, addr, exc) -> None:
        self._die(f"descriptor feed to {addr} failed: {exc!r}")

    # -- the single dispatch authority ------------------------------------

    def _worker_loop(self) -> None:
        """The ONLY thread that dispatches device programs in this
        process — the global program order is the descriptor order,
        identical on every process by construction (rule 2/3)."""
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                return
            try:
                if item[0] == "reset":
                    self._do_reset(*item[1:])
                else:
                    self._do_round(*item[1:])
            except Exception as e:                    # noqa: BLE001
                self._die(f"worker dispatch failed: {e!r}")
                if item[0] == "round" and item[3] is not None:
                    item[3].put(None)
                return

    def _do_reset(self, gen: int, leader: int, term: int,
                  first_idx: int) -> None:
        with self.lock:
            if term < self._term or gen <= self._worker_gen:
                return                  # stale leadership's reset
        devlog = self._fresh_devlog(first_idx, leader, term)
        with self.lock:
            self._devlog = devlog
            self._worker_gen = gen
            self.generation = max(self.generation, gen)
            self._leader, self._term = leader, term
            if self.idx != leader:
                # Leader-side _next_end0 was set synchronously in
                # reset() and may already have advanced past first_idx
                # by the time this queue item runs — never clobber it.
                self._next_end0 = first_idx
            self._expect_seq = 0
            self.stats["resets"] += 1
        if self.logger is not None:
            self.logger.info("mesh plane reset: gen=%d leader=%d term=%d "
                             "base=%d", gen, leader, term, first_idx)

    def _do_round(self, desc: _RoundDesc, encoded, result_q) -> None:
        """Dispatch one window.  ``encoded`` is the leader's staged
        window or None (follower).  ``result_q`` (leader only) receives
        the window handle.  ALWAYS dispatches (rule 3) unless the
        plane is dead."""
        sdata, smeta = self._stage_local(encoded)
        daemon = self._daemon
        lock = daemon.lock if daemon is not None else threading.RLock()
        with lock:
            with self.lock:
                if self._devlog is None:
                    raise RuntimeError("round before any reset/warmup")
                poisoned = desc.gen != self._worker_gen
                if not poisoned and desc.seq != self._expect_seq:
                    # A gap in the CURRENT generation's stream means a
                    # descriptor was lost: pairing can't be maintained.
                    raise RuntimeError(
                        f"descriptor gap: seq {desc.seq} != "
                        f"{self._expect_seq}")
                if not poisoned:
                    self._expect_seq = desc.seq + 1
            # Term check under the DAEMON lock (election safety): a
            # round below our daemon's current term is poisoned — the
            # in-collective vote fence.
            node_term = (daemon.node.current_term
                         if daemon is not None else desc.term)
            if desc.term < node_term:
                poisoned = True
            if poisoned:
                ctrl = self._ctrl(-3, max(node_term, desc.term) + 1,
                                  desc.end0, desc.mask_old, desc.mask_new,
                                  desc.q_old, desc.q_new)
            else:
                ctrl = self._ctrl(desc.leader, desc.term, desc.end0,
                                  desc.mask_old, desc.mask_new,
                                  desc.q_old, desc.q_new)
            import time as _time
            _t0 = _time.monotonic()
            # The pipe does NOT donate (see _build), so the previous
            # devlog's buffers stay valid after dispatch: a shard
            # reader that grabbed self._devlog concurrently reads
            # stale-but-valid data, never a deleted buffer.  (The
            # donating variant killed follower planes under sustained
            # traffic — the drain's shard_end raced one dispatch per
            # ~2k ops and materialized a deleted array; and holding
            # self.lock across dispatch+materialize instead would
            # park _die/quiesce/_do_round behind a stuck collective.)
            with self.lock:
                devlog = self._devlog
            new_devlog, commits, _ = self._pipe(devlog, sdata,
                                                smeta, ctrl)
            with self.lock:
                self._devlog = new_devlog
            _ms = (_time.monotonic() - _t0) * 1e3
            self.stats["max_dispatch_ms"] = max(
                self.stats.get("max_dispatch_ms", 0.0), _ms)
            if _ms > 50.0 and self.logger is not None:
                self.logger.warning("mesh dispatch blocked %.0f ms "
                                    "(seq=%d, daemon lock held)",
                                    _ms, desc.seq)
            with self.lock:
                K = self.FIXED_WINDOW
                if poisoned:
                    self.stats["poisoned_rounds"] += 1
                else:
                    self.stats["rounds"] += K
                    self.stats["entries_devplane"] += K * self.batch
                    self.stats["pipelined_dispatches"] += 1
                    self.depth_histogram[K] = \
                        self.depth_histogram.get(K, 0) + 1
                h = MeshWindowHandle(desc.gen, desc.end0,
                                     self.FIXED_WINDOW, commits,
                                     poisoned=poisoned)
                self._outstanding.append(h)
        if result_q is not None:
            result_q.put(h)
        # Follower pacing: bound the dispatched-unresolved pipeline so a
        # backend failure surfaces promptly here (deactivating the
        # plane) instead of silently poisoning the donated-array chain.
        self._prune_outstanding(limit=4)

    #: How long any blocking wait on a window may take before the plane
    #: is declared dead.  The backend gives NO deadline of its own: a
    #: collective missing one participant blocks until that process
    #: EXITS (probed empirically — 400 s with both ends alive), so
    #: every wait polls is_ready() against this budget instead of
    #: parking forever.  Normal windows complete in milliseconds; the
    #: budget only trips when a descriptor was lost or a peer wedged,
    #: both of which already mean the plane must degrade to TCP.  Sized
    #: WELL above worst-case scheduling stalls on an oversubscribed
    #: box (a saturated 1-core host showed 10 s was trippable by CPU
    #: starvation alone, killing healthy planes).
    WAIT_BUDGET_S = 45.0

    def _wait_window(self, h: "MeshWindowHandle", what: str):
        """Readiness-polled wait; returns the commits ndarray or None
        after killing the plane (timeout or collective error)."""
        import time as _time
        deadline = _time.monotonic() + self.WAIT_BUDGET_S
        try:
            while not h.commits.is_ready():
                if _time.monotonic() > deadline:
                    self._die(f"{what}: window never completed "
                              f"(missing participant?)")
                    return None
                if self._stop.is_set():
                    return None
                _time.sleep(0.0005)
            return np.asarray(h.commits)
        except Exception as e:                        # noqa: BLE001
            self._die(f"{what} failed: {e!r}")
            return None

    def _prune_outstanding(self, limit: int) -> None:
        while True:
            with self.lock:
                if len(self._outstanding) <= limit:
                    return
                h = self._outstanding[0]
            if self._wait_window(h, "window") is None:
                return
            with self.lock:
                if self._outstanding and self._outstanding[0] is h:
                    self._outstanding.pop(0)

    def quiesce_ready(self) -> bool:
        """Non-blocking pre-vote coverage check (module docstring,
        election safety): True iff every window this process has
        DISPATCHED is executed (its writes are in the shard, ready for
        the pre-vote drain) or the plane is dead (a dead plane's
        unresolved windows never produced a commit anyone adopted).

        Returns False — VOTE VETO — while windows are still executing:
        the election layer defers a tick instead of blocking, so the
        daemon keeps ticking/serving while e.g. a dead leader's
        half-dispatched collective takes seconds to error out.  A
        window that stays unready past WAIT_BUDGET_S kills the plane
        (the backend itself never times out; probed empirically)."""
        import time as _time
        if self.dead:
            return True
        with self.lock:
            outstanding = list(self._outstanding)
        for h in outstanding:
            try:
                ready = h.commits.is_ready()
            except Exception as e:                    # noqa: BLE001
                self._die(f"quiesce: window failed: {e!r}")
                return True
            if not ready:
                now = _time.monotonic()
                if self._quiesce_since is None:
                    self._quiesce_since = now
                elif now - self._quiesce_since > self.WAIT_BUDGET_S:
                    self._die("quiesce: window never completed "
                              "(missing participant?)")
                    return True
                return False
        self._quiesce_since = None
        with self.lock:
            self._outstanding = [h for h in self._outstanding
                                 if h not in outstanding]
        return True

    # -- leader-facing surface (DevicePlaneDriver) ------------------------

    def reset(self, leader: int, term: int,
              first_idx: int) -> Optional[int]:
        """New leadership: fence the descriptor stream + fresh shards on
        every process.  Only meaningful on the leader's process
        (leader == self.idx)."""
        if self.dead or not self.ready:
            return None
        assert leader == self.idx, (leader, self.idx)
        with self.lock:
            if term < self._term:
                return None
            gen = self.generation + 1
            self.generation = gen
            self._term = term
            self._leader = leader
            self._next_end0 = first_idx
            self._seq = 0
        payload = (wire.u8(OP_MESH) + wire.u8(_SUB_RESET) + wire.u64(gen)
                   + wire.u8(leader) + wire.u64(term)
                   + wire.u64(first_idx))
        self._broadcast(payload)
        self._q.put(("reset", gen, leader, term, first_idx))
        if self.dead:
            return None
        return gen

    def _broadcast(self, payload: bytes) -> None:
        for r in range(self.n_replicas):
            if r == self.idx:
                continue
            feed = self._feeds.get(r)
            if feed is None or feed.dead:
                addr = self._peer_addr(r)
                if addr is None:
                    self._die(f"no control endpoint for mesh peer {r}")
                    return
                feed = self._feeds[r] = _PeerFeed(addr, self._feed_dead)
            feed.send(payload)

    def _peer_addr(self, r: int) -> Optional[tuple]:
        peers = self.spec.peers
        if r >= len(peers) or not peers[r]:
            return None
        host, port = peers[r].rsplit(":", 1)
        return host, int(port)

    def commit_rounds_async(self, gen: int, end0: int,
                            entries: list[LogEntry], cid,
                            live: set[int]) -> Optional[MeshWindowHandle]:
        """Stage + describe + dispatch one fixed window without waiting
        for its result (collect via resolve_rounds).  ``entries`` must
        be exactly FIXED_WINDOW * batch, idx-contiguous from end0."""
        if self.dead or not self.ready:
            return None
        K, B, SB = self.FIXED_WINDOW, self.batch, self.slot_bytes
        assert len(entries) == K * B, (len(entries), K, B)
        with self.lock:
            if gen != self.generation:
                return None
            if end0 != self._next_end0:
                return None
            term = self._term
            seq = self._seq
            self._seq += 1
            self._next_end0 = end0 + K * B
        bd = np.zeros((K, B, SB), np.uint8)
        bm = np.zeros((K, B, 4), np.int32)
        for k in range(K):
            self._encode_batch(entries[k * B:(k + 1) * B], end0 + k * B,
                               bd[k], bm[k])
        from apus_tpu.core.cid import CidState
        R = self.n_replicas
        mask_old = [1 if (cid.contains(i) and i < cid.size) else 0
                    for i in range(R)]
        if cid.state == CidState.TRANSIT:
            mask_new = [1 if (cid.contains(i) and i < cid.new_size) else 0
                        for i in range(R)]
            q_new = quorum_size(cid.new_size)
        else:
            mask_new, q_new = [0] * R, 0
        desc = _RoundDesc(gen, seq, self.idx, term, end0, mask_old,
                          mask_new, quorum_size(cid.size), q_new)
        self._broadcast(desc.encode())
        if self.dead:
            return None
        result_q: "queue.Queue" = queue.Queue(maxsize=1)
        self._q.put(("round", desc, (bd, bm), result_q))
        # Blocks only for the worker's ENQUEUE of the program (it
        # dispatches promptly), not for execution.  Dead-aware wait: if
        # the worker died on an EARLIER queue item, our item may never
        # be serviced (the _die drain and this poll race; either way
        # the caller must not park forever).
        while True:
            try:
                h = result_q.get(timeout=0.5)
                break
            except queue.Empty:
                if self.dead:
                    return None
        if h is not None and h.poisoned:
            return None
        return h

    def _encode_batch(self, entries, end0, out_data, out_meta) -> None:
        SB = self.slot_bytes
        flat = memoryview(out_data.reshape(-1))
        for j, e in enumerate(entries):
            assert e.idx == end0 + j, (e.idx, end0, j)
            size = wire.entry_wire_size(e)
            if size > SB:
                raise ValueError(f"entry {e.idx} wire size {size} > slot "
                                 f"{SB}; segment upstream")
            wire.encode_entry_into(e, flat, j * SB)
            out_meta[j] = (e.req_id & 0x7FFFFFFF, e.clt_id & 0x7FFFFFFF,
                           int(e.type), size)

    def commit_rounds(self, gen: int, end0: int, entries, cid,
                      live) -> Optional[int]:
        h = self.commit_rounds_async(gen, end0, entries, cid, live)
        return None if h is None else self.resolve_rounds(h)

    def commit_round(self, gen, end0, entries, cid, live):
        raise NotImplementedError(
            "mesh plane dispatches fixed windows only (FIXED_WINDOW)")

    def resolve_rounds(self, h: MeshWindowHandle) -> Optional[int]:
        commits_host = self._wait_window(h, "resolve")
        if commits_host is None:
            return None
        B = self.batch
        with self.lock:
            if self._outstanding and h in self._outstanding:
                self._outstanding.remove(h)
            if h.gen != self.generation or h.poisoned:
                return None
            self.stats["quorum_fail_rounds"] += int(sum(
                int(commits_host[k]) < h.end0 + (k + 1) * B
                for k in range(h.K)))
        return int(commits_host[-1])

    # -- descriptor receive path (PeerServer extra op) --------------------

    def on_descriptor(self, r: wire.Reader) -> bytes:
        """Runs on a PeerServer connection thread (no node lock)."""
        if not self.ready and not self.dead:
            # Descriptors can only flow once every process passed the
            # warmup RENDEZVOUS — so "not ready" here means our build
            # thread is in its last milliseconds of bookkeeping while a
            # faster peer's already dispatched.  Wait it out briefly (a
            # nack would kill the whole plane over a thread-scheduling
            # race); a build that really failed flips ``dead``.
            import time as _time
            deadline = _time.monotonic() + 30.0
            while not self.ready and not self.dead \
                    and _time.monotonic() < deadline:
                _time.sleep(0.005)
        if self.dead or not self.ready:
            return wire.u8(wire.ST_ERROR)
        sub = r.u8()
        if sub == _SUB_RESET:
            gen = r.u64()
            leader, term, first_idx = r.u8(), r.u64(), r.u64()
            self._q.put(("reset", gen, leader, term, first_idx))
            return wire.u8(wire.ST_OK)
        if sub == _SUB_ROUND:
            desc = _RoundDesc.decode(r)
            self._q.put(("round", desc, None, None))
            return wire.u8(wire.ST_OK)
        return wire.u8(wire.ST_ERROR)

    # -- local shard readback ---------------------------------------------

    def _local_shard(self, arr):
        shards = arr.addressable_shards
        assert len(shards) == 1, len(shards)
        return shards[0].data            # [1, ...] on our device

    def shard_end(self, replica: int, gen: int) -> Optional[int]:
        """Reads stay LOCAL and remain available even when the plane is
        dead — the follower drain (and the pre-vote drain) must still
        absorb rows completed windows landed in our shard (see _die)."""
        from apus_tpu.ops.logplane import OFF_END
        if replica != self.idx:
            return None                 # only our own shard is local
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            offs = self._devlog.offs
        # Materialize OUTSIDE the lock: the pipe does not donate (see
        # _build), so this reference stays valid even if a new round
        # dispatches+swaps concurrently; the sync here parks only THIS
        # reader until the producing round completes.
        try:
            row = np.asarray(self._local_shard(offs))
        except Exception as e:                        # noqa: BLE001
            self._die(f"shard read failed: {e!r}")
            return None
        return int(row[0, OFF_END])

    def read_rows(self, replica: int, gen: int, lo: int, hi: int,
                  window: bool = False) -> Optional[list[LogEntry]]:
        from apus_tpu.ops.logplane import META_IDX, META_LEN, slot_of
        if replica != self.idx:
            return None
        cap = self.batch * (self.FIXED_WINDOW if window else 1)
        hi = min(hi, lo + cap)
        slots = slot_of(lo + np.arange(hi - lo, dtype=np.int64),
                        self.n_slots).astype(np.int32)
        with self.lock:
            if gen != self.generation or self._devlog is None:
                return None
            if hi <= lo:
                return []
            data_arr, meta_arr = self._devlog.data, self._devlog.meta
        # Bulk copy OUTSIDE the lock — non-donated buffers stay valid
        # (see shard_end); holding self.lock across a whole-shard
        # device sync would serialize _do_round (which waits on it
        # while holding the daemon lock) behind every drain.
        try:
            data = np.asarray(self._local_shard(data_arr))[0][slots]
            meta = np.asarray(self._local_shard(meta_arr))[0][slots]
        except Exception as e:                        # noqa: BLE001
            self._die(f"shard read failed: {e!r}")
            return None
        out: list[LogEntry] = []
        for j, idx in enumerate(range(lo, hi)):
            if int(meta[j, META_IDX]) != idx:
                break
            n = int(meta[j, META_LEN])
            blob = data[j, :n].tobytes()
            try:
                e = wire.decode_entry(wire.Reader(blob))
            except Exception:                         # noqa: BLE001
                break
            if e.idx != idx:
                break
            out.append(e)
        return out


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apus_tpu.runtime.mesh_plane",
        description="Host the mesh-plane coordination service "
                    "(one per cluster, outside every replica).")
    ap.add_argument("--serve-coordinator", required=True, metavar="ADDR",
                    help="host:port to bind the coordination service on")
    ap.add_argument("--n", type=int, required=True,
                    help="number of mesh processes (replicas)")
    a = ap.parse_args()
    serve_coordinator(a.serve_coordinator, a.n)
