"""Overload control plane (ISSUE 17): typed admission, backpressure,
and load shedding across the serving stack.

Every queue in the serving path used to be effectively unbounded, so
the first honest ramp past saturation produced the classic collapse:
queues eat memory, latency blows past client deadlines, retries
amplify offered load, and control traffic (HB/vote/lease) queues
behind client bursts until a pure-overload condition burns a
leadership.  This module makes overload a CONTROLLED, OBSERVABLE,
TYPED condition instead:

- ``ST_OVERLOAD`` — a typed wire status (value 10 in the client-op
  status namespace, next free after WRONG_GROUP=8/MIGRATING=9).  A
  shed reply carries a retry-after hint (u32 LE milliseconds in the
  standard blob body) and is emitted BEFORE admission: a shed op is
  provably never submitted to any log, so exactly-once and the audit
  plane's ambiguity taxonomy are untouched (a shed is a deterministic
  refusal, like WRONG_GROUP — not an ambiguous timeout).
- :class:`AdmissionGate` — the server-side bounded in-flight budget
  (global + per-connection), consulted by PeerServer's ingest path
  and mirrored natively by ``native/dataplane.cpp`` (which counts
  in-flight frames and sheds before crossing the GIL).
- :class:`OverloadPolicy` — the per-daemon knob bundle (env-tunable:
  ``APUS_OVL_*``), including the deadline-aware shed at the
  group-commit drain (ops whose client deadline already expired by
  the time the burst wins the node lock are dropped pre-admission).
- :class:`RetryBudget` (token bucket) + :class:`CircuitBreaker` —
  the client-side cooperation half: retries against an overloaded
  peer are budgeted so retry amplification cannot multiply offered
  load, and a run of consecutive sheds trips a breaker that fails
  fast (typed) for a cooloff window instead of hammering the peer.

Strict control-traffic priority is enforced at the call sites: only
client data ops (OP_CLT_WRITE/OP_CLT_READ, bare or OP_GROUP-wrapped)
are ever counted against budgets or shed — HB/vote/lease/CONFIG/
snapshot frames bypass the gate entirely, so overload can never
starve the consensus plane of its own control messages.
"""

from __future__ import annotations

import os
import struct
import threading
import time

#: Typed shed status, client-op namespace (NOT_LEADER=4, TIMEOUT=5,
#: WRONG_GROUP=8, MIGRATING=9 are taken; 10 is the next free value).
#: Mirrored in native/dataplane.cpp and apus_tpu/load/openloop.py.
ST_OVERLOAD = 10

#: Default retry-after hint carried by shed replies (milliseconds).
DEFAULT_RETRY_AFTER_MS = 50

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def shed_reply(req_id: int, retry_after_ms: int = DEFAULT_RETRY_AFTER_MS
               ) -> bytes:
    """The canonical shed reply: ``u8 ST_OVERLOAD | u64 req_id |
    u32 4 | u32 retry_after_ms``.  native/dataplane.cpp builds the
    SAME bytes (the cross-impl equivalence tape pins it)."""
    return (bytes([ST_OVERLOAD]) + _U64.pack(req_id)
            + _U32.pack(4) + _U32.pack(max(0, int(retry_after_ms))))


def parse_retry_after(resp: bytes) -> int:
    """Retry-after hint (ms) from a shed reply; the default when the
    body is absent/short (forward compat)."""
    if len(resp) >= 17:
        n = _U32.unpack_from(resp, 9)[0]
        if n >= 4 and len(resp) >= 13 + 4:
            return _U32.unpack_from(resp, 13)[0]
    return DEFAULT_RETRY_AFTER_MS


class Overloaded(TimeoutError):
    """Raised by ApusClient when an op was typed-shed and the retry
    budget/breaker refuses further attempts.  Subclasses TimeoutError
    so existing deadline handlers keep working; carries the server's
    retry-after hint for gateways that propagate backpressure."""

    def __init__(self, msg: str,
                 retry_after_ms: int = DEFAULT_RETRY_AFTER_MS):
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms


class AdmissionGate:
    """Bounded global in-flight budget for client data ops.

    ``acquire(want)`` grants admission for the FIFO prefix of a burst
    (0..want ops); the caller sheds the remainder with typed replies
    and MUST ``release(granted)`` once the admitted ops have replied.
    ``max_inflight <= 0`` disables the global bound (the gate still
    tracks in-flight for the queue-depth gauge)."""

    def __init__(self, max_inflight: int = 0):
        self.max_inflight = max_inflight
        self._mu = threading.Lock()
        self._inflight = 0
        #: High-water mark since last scrape (queue-depth evidence in
        #: failure dumps even when the scrape races the burst).
        self.peak_inflight = 0

    @property
    def inflight(self) -> int:
        return self._inflight

    def acquire(self, want: int) -> int:
        if want <= 0:
            return 0
        with self._mu:
            if self.max_inflight > 0:
                room = self.max_inflight - self._inflight
                granted = max(0, min(want, room))
            else:
                granted = want
            self._inflight += granted
            if self._inflight > self.peak_inflight:
                self.peak_inflight = self._inflight
            return granted

    def release(self, n: int) -> None:
        if n <= 0:
            return
        with self._mu:
            self._inflight = max(0, self._inflight - n)


class OverloadPolicy:
    """Per-daemon overload knobs + counters (one instance per daemon,
    shared by PeerServer, the group-commit drain, and the native
    plane's Python glue).

    Budgets default generous — normal workloads never trip them —
    and every knob is env-tunable so chaos campaigns can shrink them:

    - ``APUS_OVL_MAX_INFLIGHT``  global admitted client ops (def 4096)
    - ``APUS_OVL_MAX_PER_CONN``  per-connection burst budget (def 256)
    - ``APUS_OVL_MAX_NATIVE``    native-plane in-flight frames budget
                                 (def = global budget)
    - ``APUS_OVL_DEADLINE_S``    drain-shed deadline (def = the
                                 daemon's client_op_timeout; <=0 off)
    - ``APUS_OVL_RETRY_MS``      retry-after hint (def 50)
    """

    def __init__(self, max_inflight: int = 4096, max_per_conn: int = 256,
                 max_native_inflight: int = 0, deadline_s: float = 5.0,
                 retry_after_ms: int = DEFAULT_RETRY_AFTER_MS,
                 stats=None, flight=None):
        self.gate = AdmissionGate(max_inflight)
        self.max_per_conn = max_per_conn
        self.max_native_inflight = (max_native_inflight
                                    if max_native_inflight > 0
                                    else max_inflight)
        self.deadline_s = deadline_s
        self.retry_after_ms = retry_after_ms
        #: srv_* metrics view (daemon installs its ObsHub view; a bare
        #: policy counts locally so tests need no hub).
        self.stats = stats
        self.flight = flight
        self._mu = threading.Lock()
        self.admitted = 0
        self.shed_global = 0
        self.shed_conn = 0
        self.shed_deadline = 0
        self._shed_note_edge = False

    @classmethod
    def from_env(cls, client_op_timeout: float = 5.0, stats=None,
                 flight=None) -> "OverloadPolicy":
        def _i(name, dflt):
            try:
                return int(os.environ.get(name, dflt))
            except ValueError:
                return dflt

        def _f(name, dflt):
            try:
                return float(os.environ.get(name, dflt))
            except ValueError:
                return dflt

        return cls(
            max_inflight=_i("APUS_OVL_MAX_INFLIGHT", 4096),
            max_per_conn=_i("APUS_OVL_MAX_PER_CONN", 256),
            max_native_inflight=_i("APUS_OVL_MAX_NATIVE", 0),
            deadline_s=_f("APUS_OVL_DEADLINE_S", client_op_timeout),
            retry_after_ms=_i("APUS_OVL_RETRY_MS",
                              DEFAULT_RETRY_AFTER_MS),
            stats=stats, flight=flight)

    # -- accounting --------------------------------------------------------

    def on_admitted(self, n: int) -> None:
        if n <= 0:
            return
        with self._mu:
            self.admitted += n
            self._shed_note_edge = False
        if self.stats is not None:
            self.stats.bump("ovl_admitted", n)

    def _note_shed(self, reason: str, n: int) -> None:
        """Flight-ring note, edge-triggered: the FIRST shed of a burst
        episode is recorded (with the queue depth beside it), then the
        edge re-arms on the next successful admission — a sustained
        shed storm is one note, not a ring flood."""
        if self.flight is None:
            return
        with self._mu:
            if self._shed_note_edge:
                return
            self._shed_note_edge = True
        try:
            self.flight.note("overload", "shed", reason=reason, n=n,
                             inflight=self.gate.inflight)
        except Exception:                                 # noqa: BLE001
            pass

    def on_shed(self, reason: str, n: int) -> None:
        if n <= 0:
            return
        with self._mu:
            if reason == "conn":
                self.shed_conn += n
            elif reason == "deadline":
                self.shed_deadline += n
            else:
                self.shed_global += n
        if self.stats is not None:
            self.stats.bump(f"ovl_shed_{reason}", n)
        self._note_shed(reason, n)

    def status(self, native_counters: "dict | None" = None) -> dict:
        """The OP_STATUS / failure-dump view: budgets, queue depth,
        shed-by-reason counters, native mirror."""
        d = {"max_inflight": self.gate.max_inflight,
             "max_per_conn": self.max_per_conn,
             "deadline_s": self.deadline_s,
             "retry_after_ms": self.retry_after_ms,
             "inflight": self.gate.inflight,
             "peak_inflight": self.gate.peak_inflight,
             "admitted": self.admitted,
             "shed_global": self.shed_global,
             "shed_conn": self.shed_conn,
             "shed_deadline": self.shed_deadline}
        if native_counters:
            d["shed_native"] = int(native_counters.get("sheds", 0))
        d["shed_total"] = (d["shed_global"] + d["shed_conn"]
                           + d["shed_deadline"]
                           + d.get("shed_native", 0))
        return d


class RetryBudget:
    """Per-peer client retry token bucket: ``rate`` tokens/s up to
    ``burst``.  A retry against an overloaded peer spends one token;
    an empty bucket means the client STOPS retrying (typed Overloaded
    to the caller) instead of amplifying offered load — the
    metastable-failure signature this PR exists to disprove."""

    def __init__(self, rate: float = 10.0, burst: int = 20):
        self.rate = rate
        self.burst = max(1, burst)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        self._mu = threading.Lock()
        self.denied = 0

    def try_spend(self, n: float = 1.0) -> bool:
        with self._mu:
            now = time.monotonic()
            self._tokens = min(float(self.burst),
                               self._tokens + (now - self._last)
                               * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            self.denied += 1
            return False

    @property
    def tokens(self) -> float:
        with self._mu:
            return self._tokens


class CircuitBreaker:
    """Consecutive-shed circuit breaker: ``threshold`` sheds in a row
    open the breaker for ``cooloff_s``; while open, calls fail fast
    (typed) without touching the wire.  After the cooloff ONE probe is
    allowed through (half-open); success closes, another shed re-opens
    with the cooloff re-armed."""

    def __init__(self, threshold: int = 8, cooloff_s: float = 1.0):
        self.threshold = max(1, threshold)
        self.cooloff_s = cooloff_s
        self._mu = threading.Lock()
        self._fails = 0
        self._open_until = 0.0
        self._half_open = False
        self.trips = 0

    @property
    def state(self) -> str:
        with self._mu:
            if self._open_until <= 0:
                return "closed"
            if time.monotonic() < self._open_until:
                return "open"
            return "half-open"

    def allow(self) -> bool:
        with self._mu:
            if self._open_until <= 0:
                return True
            now = time.monotonic()
            if now < self._open_until:
                return False
            if self._half_open:
                return False          # one probe already in flight
            self._half_open = True
            return True

    def record_ok(self) -> None:
        with self._mu:
            self._fails = 0
            self._open_until = 0.0
            self._half_open = False

    def record_shed(self) -> None:
        with self._mu:
            self._fails += 1
            if self._half_open or self._fails >= self.threshold:
                self._open_until = time.monotonic() + self.cooloff_s
                self._half_open = False
                self._fails = 0
                self.trips += 1

    def snapshot(self) -> dict:
        return {"state": self.state, "trips": self.trips}


def backoff_s(attempt: int, retry_after_ms: int, rng_u: float,
              cap_s: float = 1.0) -> float:
    """Jittered exponential backoff honoring the server hint: base is
    the retry-after, doubled per attempt, full jitter in [0.5, 1.5),
    capped.  ``rng_u`` is a uniform [0,1) draw (caller owns the RNG so
    seeded harnesses stay deterministic)."""
    base = max(0.001, retry_after_ms / 1000.0)
    return min(cap_s, base * (1 << min(attempt, 8))) * (0.5 + rng_u)
