"""Durable persistence of applied commands + snapshot build/apply.

Parity with the reference's stable-storage path: every captured request
is persisted to BerkeleyDB (stablestorage_store_cmd, proxy.c:269-291),
the SM snapshot *is* the DB dump (stablestorage_dump_records,
proxy.c:300), and applying a snapshot both re-stores and replays it
(proxy.c:306-339).

Design difference (deliberate): the reference persists entries at
replication time, pre-commit (persist_new_entries,
dare_server.c:1792-1810), so its store can contain entries that never
commit.  We persist at apply time — the store is always a prefix of the
committed, applied log, which makes restart recovery exact: replay the
store into the SM + endpoint DB, then catch up the rest from peers.
"""

from __future__ import annotations

import os
from typing import Optional

from apus_tpu.core.epdb import EndpointDB
from apus_tpu.core.log import LogEntry
from apus_tpu.models.sm import Snapshot, StateMachine
from apus_tpu.parallel import wire
from apus_tpu.utils.store import open_store, parse_dump

#: On-disk record layout magic.  The wire LogEntry layout is shared
#: with the network protocol, which may evolve; the 4-byte magic makes a
#: stale store fail loudly instead of decoding garbage — deterministic,
#: unlike a 1-byte version that a v1 record's idx LSB could collide
#: with.  (APR1 was a dev format with u32 clt_id; APR2 widened it.)
RECORD_MAGIC = b"APR2"


class Persistence:
    """Attach to a ReplicaDaemon: persists every applied CSM entry."""

    def __init__(self, path: str, prefer_native: bool = True):
        self.store = open_store(path, prefer_native=prefer_native)

    def on_commit(self, e: LogEntry) -> None:
        self.store.append(RECORD_MAGIC + wire.encode_entry(e))

    # -- snapshots --------------------------------------------------------

    def snapshot(self) -> Snapshot:
        """The snapshot is the store dump (proxy.c:300 analog).  One
        dump serves both the payload and the last determinant."""
        blob = self.store.dump()
        e = last_record_entry(blob)
        last_idx, last_term = (e.idx, e.term) if e else (0, 0)
        return Snapshot(last_idx, last_term, blob)

    def apply_snapshot(self, snap: Snapshot, sm: StateMachine,
                       epdb: EndpointDB) -> None:
        """Replace the store with the snapshot and replay it
        (proxy.c:306-339 analog: re-store + replay every record)."""
        self.store.load_dump(snap.data)
        replay(self.store.records(), sm, epdb)

    # -- recovery ---------------------------------------------------------

    def last_determinant(self) -> tuple[int, int]:
        e = last_record_entry(self.store.dump())
        return (e.idx, e.term) if e else (0, 0)

    def replay_into(self, sm: StateMachine, epdb: EndpointDB) -> int:
        """Rebuild SM + endpoint-DB state from the store; returns the
        next log index to fetch from peers (apply floor)."""
        recs = self.store.records()
        replay(recs, sm, epdb)
        if not recs:
            return 1
        return decode_record(recs[-1]).idx + 1

    def close(self) -> None:
        self.store.close()


def decode_record(rec: bytes) -> LogEntry:
    if rec[:4] != RECORD_MAGIC:
        raise ValueError(
            f"unsupported store record format {rec[:4]!r} "
            f"(expected {RECORD_MAGIC!r}); refusing to decode")
    return wire.decode_entry(wire.Reader(rec[4:]))


def last_record_entry(blob: bytes):
    """Decode the final record of a dump, or None if empty."""
    recs = parse_dump(blob)
    return decode_record(recs[-1]) if recs else None


def replay(records: list[bytes], sm: StateMachine,
           epdb: EndpointDB) -> None:
    for rec in records:
        e = decode_record(rec)
        reply = sm.apply(e.idx, e.data)
        epdb.note_applied(e.clt_id, e.req_id, e.idx, reply)


def daemon_store_path(db_dir: str, idx: int) -> str:
    os.makedirs(db_dir, exist_ok=True)
    return os.path.join(db_dir, f"apus_records.{idx}.db")
