"""Durable persistence of applied commands + installed snapshots.

Parity with the reference's stable-storage path: every captured request
is persisted to BerkeleyDB (stablestorage_store_cmd, proxy.c:269-291)
and applying a snapshot re-stores its records (proxy.c:306-339).

Design difference (deliberate): the reference persists entries at
replication time, pre-commit (persist_new_entries,
dare_server.c:1792-1810), so its store can contain entries that never
commit.  We persist at apply time — the store is always a prefix of the
committed, applied log — and we persist installed snapshots as store
records too, so a replica that caught up via snapshot push still
recovers its full state on restart: replay scans the store in order,
resetting at each snapshot record and applying entry records after it.
"""

from __future__ import annotations

import os
import struct

from apus_tpu.core.epdb import EndpointDB
from apus_tpu.core.log import LogEntry
from apus_tpu.models.sm import Snapshot, StateMachine
from apus_tpu.parallel import wire
from apus_tpu.utils.store import open_store

#: On-disk record layout magics.  The wire LogEntry layout is shared
#: with the network protocol, which may evolve; 4-byte magics make a
#: stale store fail loudly instead of decoding garbage.  (APR1 was a
#: dev format with u32 clt_id; APR2 widened it.)
RECORD_MAGIC = b"APR2"     # one applied log entry
SNAP_MAGIC = b"APS2"       # an installed snapshot (SM blob + epdb dump)


class Persistence:
    """Attach to a ReplicaDaemon: persists applied CSM entries and
    installed snapshots."""

    def __init__(self, path: str, prefer_native: bool = True):
        self.store = open_store(path, prefer_native=prefer_native)

    def on_commit(self, e: LogEntry) -> None:
        self.store.append(RECORD_MAGIC + wire.encode_entry(e))

    def on_snapshot(self, snap: Snapshot, ep_dump: list) -> None:
        """Record a leader-pushed snapshot install (without it, restart
        replay would rebuild from a store missing the snapshot prefix).
        The partial-chunk-group buffer (snap.seg) is part of the
        snapshot point: a restart must resume those groups or finals
        delivered during catch-up would reassemble incomplete."""
        self.store.append(
            SNAP_MAGIC + struct.pack("<QQ", snap.last_idx, snap.last_term)
            + wire.blob(snap.data) + wire.encode_ep_dump(ep_dump)
            + wire.blob(snap.seg))

    # -- recovery ---------------------------------------------------------

    def replay_into(self, sm: StateMachine, epdb: EndpointDB,
                    node=None) -> int:
        """Rebuild SM + endpoint-DB state from the store; returns the
        next log index to fetch from peers (apply floor).  With
        ``node``, a replayed snapshot's partial-chunk-group buffer is
        restored into the node's reassembler (catch-up may deliver
        finals whose early chunks predate the snapshot)."""
        nxt = 1
        for rec in self.store.records():
            kind, payload = decode_record(rec)
            if kind == "entry":
                reply = sm.apply(payload.idx, payload.data)
                epdb.note_applied(payload.clt_id, payload.req_id,
                                  payload.idx, reply)
                nxt = payload.idx + 1
            else:
                snap, ep_dump = payload
                sm.apply_snapshot(snap)
                epdb.load(ep_dump)
                if node is not None:
                    from apus_tpu.core.segment import Reassembler
                    node._seg = Reassembler.load(snap.seg)
                nxt = snap.last_idx + 1
        return nxt

    def close(self) -> None:
        self.store.close()


def decode_record(rec: bytes):
    """-> ("entry", LogEntry) | ("snapshot", (Snapshot, ep_dump))."""
    magic = rec[:4]
    if magic == RECORD_MAGIC:
        return "entry", wire.decode_entry(wire.Reader(rec[4:]))
    if magic == SNAP_MAGIC:
        last_idx, last_term = struct.unpack_from("<QQ", rec, 4)
        r = wire.Reader(rec[20:])
        data = r.blob()
        ep_dump = wire.decode_ep_dump(r)
        seg = r.blob() if r.remaining else b""
        return "snapshot", (Snapshot(last_idx, last_term, data, seg=seg),
                            ep_dump)
    raise ValueError(
        f"unsupported store record format {magic!r} "
        f"(expected {RECORD_MAGIC!r} or {SNAP_MAGIC!r}); refusing to decode")


def daemon_store_path(db_dir: str, idx: int) -> str:
    os.makedirs(db_dir, exist_ok=True)
    return os.path.join(db_dir, f"apus_records.{idx}.db")
