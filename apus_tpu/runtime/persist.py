"""Durable persistence of applied commands + installed snapshots.

Parity with the reference's stable-storage path: every captured request
is persisted to BerkeleyDB (stablestorage_store_cmd, proxy.c:269-291)
and applying a snapshot re-stores its records (proxy.c:306-339).

Design difference (deliberate): the reference persists entries at
replication time, pre-commit (persist_new_entries,
dare_server.c:1792-1810), so its store can contain entries that never
commit.  We persist at apply time — the store is always a prefix of the
committed, applied log — and we persist installed snapshots as store
records too, so a replica that caught up via snapshot push still
recovers its full state on restart: replay scans the store in order,
resetting at each snapshot record and applying entry records after it.
"""

from __future__ import annotations

import os
import struct

from apus_tpu.core.epdb import EndpointDB
from apus_tpu.core.log import LogEntry
from apus_tpu.models.sm import (REFUSED_REPLY_PREFIX, Snapshot,
                                StateMachine)
from apus_tpu.parallel import wire
from apus_tpu.utils.store import open_store

#: On-disk record layout magics.  The wire LogEntry layout is shared
#: with the network protocol, which may evolve; 4-byte magics make a
#: stale store fail loudly instead of decoding garbage.  (APR1 was a
#: dev format with u32 clt_id; APR2 widened it.)
RECORD_MAGIC = b"APR2"     # one applied log entry
SNAP_MAGIC = b"APS2"       # an installed snapshot (SM blob + epdb dump)
SNAPFILE_MAGIC = b"APF1"   # an installed snapshot whose dump lives in a
                           # SIDECAR file next to the store (streamed
                           # installs never materialize the blob, so the
                           # store record carries a filename, not data)
DELTA_MAGIC = b"APD1"      # an installed DELTA snapshot: state delta on
                           # top of the preceding record's applied
                           # determinant (replayed via
                           # sm.apply_snapshot_delta, never as a full
                           # image)


class Persistence:
    """Attach to a ReplicaDaemon: persists applied CSM entries and
    installed snapshots.

    ``sync_policy`` controls when appended records are fsynced:

    - ``"none"``: never (OS writeback only).
    - ``"batch"`` (default): the daemon calls :meth:`flush_window` once
      per group-commit drain window — one ``fdatasync`` amortized over
      every entry the window applied, not one per entry.
    - ``"always"``: fsync after every appended record.

    Durability model (see DESIGN.md "durability & recovery semantics"):
    an ACKED write's durability comes from REPLICATION — it lives on a
    quorum before the client sees OK — so fsync only narrows the
    full-cluster-power-loss window; it is not on the ack path under
    any policy.
    """

    def __init__(self, path: str, prefer_native: bool = True,
                 sync_policy: str = "batch", logger=None):
        if sync_policy not in ("none", "batch", "always"):
            raise ValueError(f"bad sync_policy {sync_policy!r}")
        self.store = open_store(path, prefer_native=prefer_native)
        self.prefer_native = prefer_native
        self.sync_policy = sync_policy
        self.logger = logger
        self._dirty = False
        #: fsync count (observability; the batch-policy test asserts
        #: syncs << appends under a pipelined burst)
        self.syncs = 0
        # -- compaction state (see compact()) -----------------------------
        #: records a restart replay must walk (everything after the
        #: last FULL snapshot record) — the compaction trigger gauge.
        self.entries_since_base = 0
        #: applied index of the last base image folded into the store
        #: (local compaction or installed snapshot); 0 = raw history.
        self.compaction_floor = 0
        #: count of local base-image folds performed this session
        self.compactions = 0
        # While a compaction is in flight the live store file is
        # FROZEN: appends queue here (the tick thread never blocks on
        # the rewrite) and drain into the new file at the swap.
        self._compacting = False
        self._cq: list[bytes] = []
        self._compact_abort = False

    def _append(self, rec: bytes) -> None:
        if self._compacting:
            self._cq.append(rec)
            return
        self.store.append(rec)

    def on_commit(self, e: LogEntry) -> None:
        self._append(RECORD_MAGIC + wire.encode_entry(e))
        self.entries_since_base += 1
        self._note_appended()

    def _note_appended(self) -> None:
        if self._compacting:
            self._dirty = True      # queued; synced after the swap
            return
        if self.sync_policy == "always":
            self._sync()
        elif self.sync_policy == "batch":
            self._dirty = True

    def _sync(self) -> None:
        self.store.sync()
        self.syncs += 1
        self._dirty = False

    def flush_window(self) -> None:
        """One sync per drain window (daemon tick, after the committed
        upcalls drained) — no-op unless the batch policy has unsynced
        appends (or while a compaction holds the file frozen)."""
        if self._compacting:
            return
        if self.sync_policy == "batch" and self._dirty:
            self._sync()

    def quarantine(self) -> str:
        """Move the store file aside (``*.corrupt``) and reopen empty —
        the undecodable-record / failed-replay policy (mirrors
        PyRecordStore's corrupt-header handling).  Returns the
        quarantine path."""
        from apus_tpu.utils.store import quarantine_path
        path = self.store.path
        try:
            self.store.close()
        except OSError:
            pass
        dst = quarantine_path(path)
        os.replace(path, dst)
        if self.logger is not None:
            self.logger.error(
                "durable store %s quarantined to %s; starting empty "
                "(this replica rejoins via catch-up)", path, dst)
        self.store = open_store(path)
        self._dirty = False
        return dst

    #: copy-chunk size for sidecar creation (one chunk resident, ever)
    _SNAP_IO_CHUNK = 1 << 20

    def on_snapshot(self, snap: Snapshot, ep_dump: list) -> None:
        """Record a leader-pushed snapshot install (without it, restart
        replay would rebuild from a store missing the snapshot prefix).
        The partial-chunk-group buffer (snap.seg) is part of the
        snapshot point: a restart must resume those groups or finals
        delivered during catch-up would reassemble incomplete.

        FILE-BACKED installs (snap.data_path, the streamed-receive
        path) stream the dump's immutable [0, data_len) prefix into a
        sidecar file next to the store and record only its name — the
        multi-GB dump is never materialized here either.  The prefix
        is valid while the SM's dump generation matches snap.data_gen
        (the install captured it); the upcall drain already discards
        stale captures (daemon._drain_upcalls order guarantees a
        superseding install's record follows).

        DELTA installs (snap.delta_base) append a DELTA record — the
        blob is a state delta on the preceding record's applied
        determinant, replayed in order via sm.apply_snapshot_delta —
        never a full snapshot record (that would silently truncate the
        replayed state to the delta)."""
        if snap.delta_base is not None:
            self._append(
                DELTA_MAGIC + struct.pack(
                    "<QQQQ", snap.last_idx, snap.last_term,
                    snap.delta_base[0], snap.delta_base[1])
                + wire.blob(snap.data) + wire.encode_ep_dump(ep_dump)
                + wire.blob(snap.seg) + wire.blob(snap.fence))
            self.entries_since_base += 1
            self._note_appended()
            return
        # A FULL install supersedes any in-flight local compaction —
        # abort it (the installed snapshot is the fresher base).
        if self._compacting:
            self._compact_abort = True
        if snap.data_path is None:
            self._append(
                SNAP_MAGIC + struct.pack("<QQ", snap.last_idx,
                                         snap.last_term)
                + wire.blob(snap.data) + wire.encode_ep_dump(ep_dump)
                + wire.blob(snap.seg) + wire.blob(snap.fence))
            self.entries_since_base = 0
            self.compaction_floor = snap.last_idx
            self._note_appended()
            return
        # Sidecar names are STORE-scoped (several daemons share a
        # db_dir in the local process deployment — proc.py passes one
        # --db-dir to every replica): deriving the prefix from this
        # store's filename keeps replica A's GC from deleting replica
        # B's restart state.
        prefix = os.path.basename(self.store.path) + ".snap."
        name = f"{prefix}{snap.last_idx}.{snap.data_gen}.bin"
        side_dir = os.path.dirname(self.store.path) or "."
        sidecar = os.path.join(side_dir, name)
        crc = _copy_sidecar(snap.data_path, sidecar, snap.data_len)
        # Record AFTER the sidecar is durable-named: a crash in between
        # leaves an orphan sidecar (harmless), never a dangling record.
        # The trailing CRC32 lets replay verify the BASE IMAGE before
        # applying it — a torn or bit-flipped sidecar quarantines and
        # re-fetches instead of priming the SM with damaged state.
        self._append(
            SNAPFILE_MAGIC + struct.pack("<QQQ", snap.last_idx,
                                         snap.last_term, snap.data_len)
            + wire.blob(name.encode()) + wire.encode_ep_dump(ep_dump)
            + wire.blob(snap.seg) + wire.blob(snap.fence)
            + wire.u32(crc))
        self.entries_since_base = 0
        self.compaction_floor = snap.last_idx
        self._note_appended()
        # GC superseded sidecars OF THIS STORE ONLY: replay only ever
        # consults the LAST snapshot record (see replay_into), so
        # earlier dumps are dead weight — without this, every streamed
        # install would leave a full-dump-size file behind forever.
        for old in os.listdir(side_dir):
            if old.startswith(prefix) and old != name \
                    and not old.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(side_dir, old))
                except OSError:
                    pass

    # -- compaction (base image + retained tail) --------------------------
    #
    # A store that only ever appends replays its WHOLE lifetime at
    # restart.  Compaction folds the applied prefix into a base image
    # (a snapshot record — sidecar-backed for dump-exposing SMs, inline
    # blob otherwise) and rewrites the file as [base] + retained tail,
    # so restart replay is bounded by the RETENTION WINDOW
    # (ClusterSpec.compact_retain entries), not history length.  The
    # daemon runs the three phases from a watchdog thread:
    #
    #   begin_compact(node)   [under the node lock]  capture the base
    #       (meta + pinned dump fd, or the cached blob) and freeze the
    #       live file — subsequent appends queue in RAM;
    #   prepare_compact(cap)  [no lock]  sidecar copy + new tmp store
    #       [base record + frozen tail] — all O(state) I/O happens
    #       here, off the tick thread;
    #   finish_compact(cap)   [under the node lock]  drain the queued
    #       appends into the tmp store, fsync, atomically swap files,
    #       reopen.  O(queue), bounded by the compaction's duration.
    #
    # Crash safety: the swap is a single os.replace; a crash before it
    # leaves the old file intact (plus a harmless orphan tmp/sidecar),
    # a crash after it finds a complete compacted store.  A FULL
    # snapshot install racing the compaction aborts it (the install is
    # the fresher base).

    def begin_compact(self, node) -> "dict | None":
        """Capture the base image under the caller-held node lock."""
        if self._compacting:
            return None
        sm = node.sm
        cap: dict = {"tail_from": self.store.count,
                     "ep_dump": node.epdb.dump()}
        last_idx, last_term = node._applied_det
        if last_idx <= 0:
            return None
        cap["meta"] = Snapshot(last_idx, last_term, b"",
                               seg=node._seg.dump(),
                               fence=node._fence_blob())
        size_of = getattr(sm, "snapshot_stream_size", None)
        total = size_of() if size_of is not None else None
        if total is not None:
            # Pin the captured image for the off-lock copy: a dup'd fd
            # (dump-file SMs — installs replace the inode, the fd keeps
            # the old bytes) or a frozen-rope reader (dump-less SMs).
            dupper = getattr(sm, "dup_dump_fd", None)
            pinner = getattr(sm, "pin_dump_reader", None)
            if dupper is not None:
                cap["dump_fd"] = dupper()
                fd = cap["dump_fd"]
                cap["read"] = (lambda off, n, _fd=fd:
                               os.pread(_fd, n, off))
            elif pinner is not None:
                cap["read"] = pinner()
            else:
                return None
            cap["total"] = total
            cap["data_gen"] = getattr(sm, "dump_generation", 0)
        else:
            snap = sm.create_snapshot(last_idx, last_term)
            cap["blob"] = snap.data
        self._compacting = True
        self._compact_abort = False
        self._cq = []
        return cap

    def prepare_compact(self, cap: dict) -> None:
        """Heavy I/O phase, no lock held: the live store file is frozen
        (appends queue) so single-threaded reads of it are safe."""
        import zlib
        meta = cap["meta"]
        tmp_path = self.store.path + ".compact"
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        from apus_tpu.utils.store import PyRecordStore
        tmp = PyRecordStore(tmp_path)
        if "read" in cap:
            prefix = os.path.basename(self.store.path) + ".snap."
            name = f"{prefix}{meta.last_idx}.c{cap['data_gen']}.bin"
            side_dir = os.path.dirname(self.store.path) or "."
            sidecar = os.path.join(side_dir, name)
            stmp = sidecar + ".tmp"
            crc = 0
            written = 0
            with open(stmp, "wb") as dst:
                while written < cap["total"]:
                    chunk = cap["read"](written,
                                        min(self._SNAP_IO_CHUNK,
                                            cap["total"] - written))
                    if not chunk:
                        raise OSError(
                            f"dump shrank during compaction capture "
                            f"({written} < {cap['total']})")
                    dst.write(chunk)
                    crc = zlib.crc32(chunk, crc)
                    written += len(chunk)
                dst.flush()
                os.fsync(dst.fileno())
            os.replace(stmp, sidecar)
            cap["sidecar"] = sidecar
            tmp.append(
                SNAPFILE_MAGIC + struct.pack(
                    "<QQQ", meta.last_idx, meta.last_term, cap["total"])
                + wire.blob(name.encode())
                + wire.encode_ep_dump(cap["ep_dump"])
                + wire.blob(meta.seg) + wire.blob(meta.fence)
                + wire.u32(crc & 0xFFFFFFFF))
        else:
            tmp.append(
                SNAP_MAGIC + struct.pack("<QQ", meta.last_idx,
                                         meta.last_term)
                + wire.blob(cap["blob"])
                + wire.encode_ep_dump(cap["ep_dump"])
                + wire.blob(meta.seg) + wire.blob(meta.fence))
        # Retained tail: every record appended after the capture point
        # (applied strictly above the base image's determinant).
        for rec in self.store.records()[cap["tail_from"]:]:
            tmp.append(rec)
        tmp.sync()
        tmp.close()
        cap["tmp_path"] = tmp_path

    def finish_compact(self, cap: dict) -> bool:
        """Swap phase, under the caller-held node lock.  Returns True
        when the compacted store took effect."""
        try:
            if self._compact_abort or "tmp_path" not in cap:
                return False
            from apus_tpu.utils.store import PyRecordStore
            tmp = PyRecordStore(cap["tmp_path"])
            tail = len(self._cq)
            for rec in self._cq:
                tmp.append(rec)
            tmp.sync()
            tmp.close()
            self.store.close()
            os.replace(cap["tmp_path"], self.store.path)
            self.store = open_store(self.store.path,
                                    prefer_native=self.prefer_native)
            self._cq = []
            self._compacting = False
            self.entries_since_base = tail
            self.compaction_floor = cap["meta"].last_idx
            self.compactions += 1
            if self.logger is not None:
                self.logger.info(
                    "store compacted: base image @ idx %d, %d retained "
                    "tail records (%d queued during the fold)",
                    cap["meta"].last_idx, self.store.count - 1, tail)
            return True
        finally:
            self.abort_compact(cap)

    def abort_compact(self, cap: "dict | None") -> None:
        """Idempotent cleanup: drain any queued appends back into the
        live store, close pinned fds, remove temp files.  Called on
        the failure/abort paths AND as finish_compact's finally (a
        no-op after a successful swap)."""
        if cap is not None:
            fd = cap.pop("dump_fd", None)
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
        if not self._compacting:
            return
        self._compacting = False
        q, self._cq = self._cq, []
        for rec in q:
            try:
                self.store.append(rec)
            except OSError:
                break
        if cap is not None and "tmp_path" in cap:
            try:
                os.unlink(cap["tmp_path"])
            except OSError:
                pass

    # -- recovery ---------------------------------------------------------

    def replay_into(self, sm: StateMachine, epdb: EndpointDB,
                    node=None) -> int:
        """Rebuild SM + endpoint-DB state from the store; returns the
        next log index to fetch from peers (apply floor).  With
        ``node``, a replayed snapshot's partial-chunk-group buffer is
        restored into the node's reassembler (catch-up may deliver
        finals whose early chunks predate the snapshot), AND the
        node's log/applied determinant are RE-BASED at the replay
        point — the replica then RESUMES replication from there
        (presenting its applied determinant, the delta-snapshot base)
        instead of re-fetching its whole history.  (The replayed store
        holds only apply-time — hence committed — records, so the
        re-base is exactly install_snapshot's.)

        An UNDECODABLE record (unknown magic / truncated payload —
        corruption the CRC frame did not catch, or a store written by
        an incompatible build) quarantines the whole store and replays
        NOTHING: raising here crash-looped the daemon forever (every
        restart re-read the same bytes), and decoding garbage is
        worse.  The replica starts empty and rejoins via snapshot
        catch-up.  Decoding is validated in a PRE-PASS so the SM and
        endpoint DB are never left holding half a replay.  A base
        image (snapfile sidecar) that is missing, short, or fails its
        recorded CRC takes the same quarantine path."""
        recs = self.store.records()
        # A FULL snapshot record is the whole state at its point, so
        # replay starts at the LAST one (cheap magic scan): everything
        # before it — entries, deltas, and earlier snapshots alike —
        # is superseded.  This also makes the sidecar GC in
        # on_snapshot sound (earlier snapfile records' sidecars are
        # never consulted) and keeps deep-history restarts O(retained
        # tail), not O(lifetime).  DELTA records never restart the
        # scan — they build on the state before them.
        start = 0
        for i, rec in enumerate(recs):
            if rec[:4] in (SNAP_MAGIC, SNAPFILE_MAGIC):
                start = i
        try:
            decoded = [decode_record(rec) for rec in recs[start:]]
        except (ValueError, struct.error, IndexError) as e:
            if self.logger is not None:
                self.logger.error("undecodable store record: %s", e)
            self.quarantine()
            return 1
        nxt = 1
        last_det = (0, 0)
        try:
            for kind, payload in decoded:
                if kind == "entry":
                    reply = sm.apply(payload.idx, payload.data)
                    # Deterministic REFUSED applies (elastic-group
                    # bucket fences) are never dedup-noted — exactly
                    # as the live apply path (core/node.py).
                    if reply is None or not reply.startswith(
                            REFUSED_REPLY_PREFIX):
                        epdb.note_applied(payload.clt_id,
                                          payload.req_id,
                                          payload.idx, reply)
                    nxt = payload.idx + 1
                    last_det = (payload.idx, payload.term)
                elif kind == "delta":
                    snap, ep_dump = payload
                    sm.apply_snapshot_delta(snap)
                    epdb.load(ep_dump)
                    if node is not None:
                        from apus_tpu.core.segment import Reassembler
                        node._seg = Reassembler.load(snap.seg)
                    nxt = snap.last_idx + 1
                    last_det = (snap.last_idx, snap.last_term)
                else:
                    if kind == "snapfile":
                        snap, ep_dump, crc = payload
                        sidecar = os.path.join(
                            os.path.dirname(self.store.path) or ".",
                            snap.data_path)
                        _verify_sidecar(sidecar, snap.data_len, crc)
                        # Never adopt: the sidecar must survive for the
                        # NEXT restart too (the SM copies chunk-wise).
                        sm.apply_snapshot_file(snap, sidecar,
                                               adopt=False)
                    else:
                        snap, ep_dump = payload
                        sm.apply_snapshot(snap)
                    epdb.load(ep_dump)
                    if node is not None:
                        from apus_tpu.core.segment import Reassembler
                        node._seg = Reassembler.load(snap.seg)
                    nxt = snap.last_idx + 1
                    last_det = (snap.last_idx, snap.last_term)
                    self.compaction_floor = snap.last_idx
                if kind != "entry" and node is not None and snap.fence:
                    node.adopt_fence(snap.fence)
        except OSError as e:
            # A snapfile record whose sidecar is missing/short/damaged
            # (deleted by hand, ENOSPC'd copy, bit rot): same policy —
            # quarantine, reset what the partial apply primed, start
            # empty.
            if self.logger is not None:
                self.logger.error("store replay failed mid-apply: %s", e)
            self.quarantine()
            # Replay starts at the last snapshot record, so the only
            # state a mid-apply failure can leave behind is that
            # snapshot's partial prime — reset it (epdb is only loaded
            # after a successful apply, so it is still clean).
            try:
                from apus_tpu.models.sm import Snapshot as _Snap
                sm.apply_snapshot(_Snap(0, 0, b""))
            except Exception:               # noqa: BLE001
                pass
            return 1
        # Replay-cost gauge: records a future restart must walk again.
        self.entries_since_base = len(decoded) - (
            1 if decoded and decoded[0][0] in ("snapshot", "snapfile")
            else 0)
        if node is not None and last_det[0] > 0:
            # RE-BASE: the log starts just past the replayed state and
            # the applied determinant presents it to the leader — the
            # foundation of bounded catch-up (tail re-replication or a
            # delta snapshot, never the full history again).
            node.log.reset(last_det[0] + 1)
            node._applied_det = last_det
        return nxt

    def close(self) -> None:
        self.store.close()


def _copy_sidecar(src: str, dst: str, length: int) -> int:
    """Chunked copy of the immutable [0, length) prefix of ``src`` into
    ``dst`` (tmp + atomic replace), returning its CRC32 — one chunk
    resident, ever.  Runs on the daemon's tick thread, so it must be
    as fast as the disk allows; the length pin freezes the captured
    prefix (appends may have grown the live dump since install)."""
    import zlib
    tmp = dst + ".tmp"
    crc = 0
    written = 0
    with open(src, "rb") as s, open(tmp, "wb") as d:
        while written < length:
            chunk = s.read(min(1 << 20, length - written))
            if not chunk:
                raise OSError(
                    f"snapshot dump {src} shorter than captured "
                    f"length {length}")
            d.write(chunk)
            crc = zlib.crc32(chunk, crc)
            written += len(chunk)
    os.replace(tmp, dst)
    return crc & 0xFFFFFFFF


def _verify_sidecar(path: str, length: int, crc: "int | None") -> None:
    """Raise OSError unless the base image at ``path`` is whole: at
    least ``length`` bytes and (when the record carries a CRC) its
    [0, length) prefix checksums clean.  The torn/bit-flipped base
    image then takes the quarantine-and-refetch path instead of
    priming the SM with damaged state."""
    import zlib
    if os.path.getsize(path) < length:
        raise OSError(f"base image {path} shorter than recorded "
                      f"length {length}")
    if crc is None:
        return
    got = 0
    left = length
    with open(path, "rb") as f:
        while left:
            chunk = f.read(min(1 << 20, left))
            if not chunk:
                raise OSError(f"base image {path} truncated mid-read")
            got = zlib.crc32(chunk, got)
            left -= len(chunk)
    if (got & 0xFFFFFFFF) != (crc & 0xFFFFFFFF):
        raise OSError(f"base image {path} fails its recorded CRC "
                      f"(disk corruption)")


def decode_record(rec: bytes):
    """-> ("entry", LogEntry) | ("snapshot", (Snapshot, ep_dump))
    | ("snapfile", (Snapshot-with-data_path=sidecar-name, ep_dump))."""
    magic = rec[:4]
    if magic == RECORD_MAGIC:
        return "entry", wire.decode_entry(wire.Reader(rec[4:]))
    if magic == SNAP_MAGIC:
        last_idx, last_term = struct.unpack_from("<QQ", rec, 4)
        r = wire.Reader(rec[20:])
        data = r.blob()
        ep_dump = wire.decode_ep_dump(r)
        seg = r.blob() if r.remaining else b""
        fence = r.blob() if r.remaining else b""
        return "snapshot", (Snapshot(last_idx, last_term, data, seg=seg,
                                     fence=fence),
                            ep_dump)
    if magic == SNAPFILE_MAGIC:
        last_idx, last_term, data_len = struct.unpack_from("<QQQ", rec, 4)
        r = wire.Reader(rec[28:])
        name = r.blob().decode()
        ep_dump = wire.decode_ep_dump(r)
        seg = r.blob() if r.remaining else b""
        fence = r.blob() if r.remaining else b""
        # Trailing base-image CRC32 (absent on pre-CRC records).
        crc = r.u32() if r.remaining >= 4 else None
        return "snapfile", (Snapshot(last_idx, last_term, b"", seg=seg,
                                     fence=fence,
                                     data_path=name, data_len=data_len),
                            ep_dump, crc)
    if magic == DELTA_MAGIC:
        last_idx, last_term, base_idx, base_term = \
            struct.unpack_from("<QQQQ", rec, 4)
        r = wire.Reader(rec[36:])
        data = r.blob()
        ep_dump = wire.decode_ep_dump(r)
        seg = r.blob() if r.remaining else b""
        fence = r.blob() if r.remaining else b""
        return "delta", (Snapshot(last_idx, last_term, data, seg=seg,
                                  fence=fence,
                                  delta_base=(base_idx, base_term)),
                         ep_dump)
    raise ValueError(
        f"unsupported store record format {magic!r} (expected "
        f"{RECORD_MAGIC!r}, {SNAP_MAGIC!r}, {SNAPFILE_MAGIC!r} or "
        f"{DELTA_MAGIC!r}); refusing to decode")


def daemon_store_path(db_dir: str, idx: int, gid: int = 0) -> str:
    """Replica ``idx``'s durable store file; ``gid`` > 0 namespaces one
    consensus group's store (elastic-group durability — each group
    replays and re-bases independently).  Group 0 keeps the legacy name
    so existing stores replay unchanged."""
    os.makedirs(db_dir, exist_ok=True)
    if gid:
        return os.path.join(db_dir, f"apus_records.{idx}.g{gid}.db")
    return os.path.join(db_dir, f"apus_records.{idx}.db")
