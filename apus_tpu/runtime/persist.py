"""Durable persistence of applied commands + installed snapshots.

Parity with the reference's stable-storage path: every captured request
is persisted to BerkeleyDB (stablestorage_store_cmd, proxy.c:269-291)
and applying a snapshot re-stores its records (proxy.c:306-339).

Design difference (deliberate): the reference persists entries at
replication time, pre-commit (persist_new_entries,
dare_server.c:1792-1810), so its store can contain entries that never
commit.  We persist at apply time — the store is always a prefix of the
committed, applied log — and we persist installed snapshots as store
records too, so a replica that caught up via snapshot push still
recovers its full state on restart: replay scans the store in order,
resetting at each snapshot record and applying entry records after it.
"""

from __future__ import annotations

import os
import struct

from apus_tpu.core.epdb import EndpointDB
from apus_tpu.core.log import LogEntry
from apus_tpu.models.sm import Snapshot, StateMachine
from apus_tpu.parallel import wire
from apus_tpu.utils.store import open_store

#: On-disk record layout magics.  The wire LogEntry layout is shared
#: with the network protocol, which may evolve; 4-byte magics make a
#: stale store fail loudly instead of decoding garbage.  (APR1 was a
#: dev format with u32 clt_id; APR2 widened it.)
RECORD_MAGIC = b"APR2"     # one applied log entry
SNAP_MAGIC = b"APS2"       # an installed snapshot (SM blob + epdb dump)
SNAPFILE_MAGIC = b"APF1"   # an installed snapshot whose dump lives in a
                           # SIDECAR file next to the store (streamed
                           # installs never materialize the blob, so the
                           # store record carries a filename, not data)


class Persistence:
    """Attach to a ReplicaDaemon: persists applied CSM entries and
    installed snapshots.

    ``sync_policy`` controls when appended records are fsynced:

    - ``"none"``: never (OS writeback only).
    - ``"batch"`` (default): the daemon calls :meth:`flush_window` once
      per group-commit drain window — one ``fdatasync`` amortized over
      every entry the window applied, not one per entry.
    - ``"always"``: fsync after every appended record.

    Durability model (see DESIGN.md "durability & recovery semantics"):
    an ACKED write's durability comes from REPLICATION — it lives on a
    quorum before the client sees OK — so fsync only narrows the
    full-cluster-power-loss window; it is not on the ack path under
    any policy.
    """

    def __init__(self, path: str, prefer_native: bool = True,
                 sync_policy: str = "batch", logger=None):
        if sync_policy not in ("none", "batch", "always"):
            raise ValueError(f"bad sync_policy {sync_policy!r}")
        self.store = open_store(path, prefer_native=prefer_native)
        self.sync_policy = sync_policy
        self.logger = logger
        self._dirty = False
        #: fsync count (observability; the batch-policy test asserts
        #: syncs << appends under a pipelined burst)
        self.syncs = 0

    def on_commit(self, e: LogEntry) -> None:
        self.store.append(RECORD_MAGIC + wire.encode_entry(e))
        self._note_appended()

    def _note_appended(self) -> None:
        if self.sync_policy == "always":
            self._sync()
        elif self.sync_policy == "batch":
            self._dirty = True

    def _sync(self) -> None:
        self.store.sync()
        self.syncs += 1
        self._dirty = False

    def flush_window(self) -> None:
        """One sync per drain window (daemon tick, after the committed
        upcalls drained) — no-op unless the batch policy has unsynced
        appends."""
        if self.sync_policy == "batch" and self._dirty:
            self._sync()

    def quarantine(self) -> str:
        """Move the store file aside (``*.corrupt``) and reopen empty —
        the undecodable-record / failed-replay policy (mirrors
        PyRecordStore's corrupt-header handling).  Returns the
        quarantine path."""
        from apus_tpu.utils.store import quarantine_path
        path = self.store.path
        try:
            self.store.close()
        except OSError:
            pass
        dst = quarantine_path(path)
        os.replace(path, dst)
        if self.logger is not None:
            self.logger.error(
                "durable store %s quarantined to %s; starting empty "
                "(this replica rejoins via catch-up)", path, dst)
        self.store = open_store(path)
        self._dirty = False
        return dst

    #: copy-chunk size for sidecar creation (one chunk resident, ever)
    _SNAP_IO_CHUNK = 1 << 20

    def on_snapshot(self, snap: Snapshot, ep_dump: list) -> None:
        """Record a leader-pushed snapshot install (without it, restart
        replay would rebuild from a store missing the snapshot prefix).
        The partial-chunk-group buffer (snap.seg) is part of the
        snapshot point: a restart must resume those groups or finals
        delivered during catch-up would reassemble incomplete.

        FILE-BACKED installs (snap.data_path, the streamed-receive
        path) stream the dump's immutable [0, data_len) prefix into a
        sidecar file next to the store and record only its name — the
        multi-GB dump is never materialized here either.  The prefix
        is valid while the SM's dump generation matches snap.data_gen
        (the install captured it); the upcall drain already discards
        stale captures (daemon._drain_upcalls order guarantees a
        superseding install's record follows)."""
        if snap.data_path is None:
            self.store.append(
                SNAP_MAGIC + struct.pack("<QQ", snap.last_idx,
                                         snap.last_term)
                + wire.blob(snap.data) + wire.encode_ep_dump(ep_dump)
                + wire.blob(snap.seg) + wire.blob(snap.fence))
            self._note_appended()
            return
        # Sidecar names are STORE-scoped (several daemons share a
        # db_dir in the local process deployment — proc.py passes one
        # --db-dir to every replica): deriving the prefix from this
        # store's filename keeps replica A's GC from deleting replica
        # B's restart state.
        prefix = os.path.basename(self.store.path) + ".snap."
        name = f"{prefix}{snap.last_idx}.{snap.data_gen}.bin"
        side_dir = os.path.dirname(self.store.path) or "."
        sidecar = os.path.join(side_dir, name)
        tmp = sidecar + ".tmp"
        # Kernel-side copy (sendfile/copy_file_range via shutil) — this
        # runs on the daemon's tick thread, so it must be as fast as
        # the disk allows; the truncate pins the captured immutable
        # prefix (appends may have grown the live dump since install).
        import shutil
        shutil.copyfile(snap.data_path, tmp)
        if os.path.getsize(tmp) < snap.data_len:
            raise OSError(
                f"snapshot dump {snap.data_path} shorter than captured "
                f"length {snap.data_len}")
        with open(tmp, "r+b") as f:
            f.truncate(snap.data_len)
        os.replace(tmp, sidecar)
        # Record AFTER the sidecar is durable-named: a crash in between
        # leaves an orphan sidecar (harmless), never a dangling record.
        self.store.append(
            SNAPFILE_MAGIC + struct.pack("<QQQ", snap.last_idx,
                                         snap.last_term, snap.data_len)
            + wire.blob(name.encode()) + wire.encode_ep_dump(ep_dump)
            + wire.blob(snap.seg) + wire.blob(snap.fence))
        self._note_appended()
        # GC superseded sidecars OF THIS STORE ONLY: replay only ever
        # consults the LAST snapshot record (see replay_into), so
        # earlier dumps are dead weight — without this, every streamed
        # install would leave a full-dump-size file behind forever.
        for old in os.listdir(side_dir):
            if old.startswith(prefix) and old != name \
                    and not old.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(side_dir, old))
                except OSError:
                    pass

    # -- recovery ---------------------------------------------------------

    def replay_into(self, sm: StateMachine, epdb: EndpointDB,
                    node=None) -> int:
        """Rebuild SM + endpoint-DB state from the store; returns the
        next log index to fetch from peers (apply floor).  With
        ``node``, a replayed snapshot's partial-chunk-group buffer is
        restored into the node's reassembler (catch-up may deliver
        finals whose early chunks predate the snapshot).

        An UNDECODABLE record (unknown magic / truncated payload —
        corruption the CRC frame did not catch, or a store written by
        an incompatible build) quarantines the whole store and replays
        NOTHING: raising here crash-looped the daemon forever (every
        restart re-read the same bytes), and decoding garbage is
        worse.  The replica starts empty and rejoins via snapshot
        catch-up.  Decoding is validated in a PRE-PASS so the SM and
        endpoint DB are never left holding half a replay."""
        recs = self.store.records()
        # A snapshot record is the FULL state at its point, so replay
        # starts at the LAST one (cheap magic scan): everything before
        # it — entries and earlier snapshots alike — is superseded.
        # This also makes the sidecar GC in on_snapshot sound (earlier
        # snapfile records' sidecars are never consulted) and keeps
        # deep-history restarts O(tail), not O(lifetime).
        start = 0
        for i, rec in enumerate(recs):
            if rec[:4] in (SNAP_MAGIC, SNAPFILE_MAGIC):
                start = i
        try:
            decoded = [decode_record(rec) for rec in recs[start:]]
        except (ValueError, struct.error, IndexError) as e:
            if self.logger is not None:
                self.logger.error("undecodable store record: %s", e)
            self.quarantine()
            return 1
        nxt = 1
        try:
            for kind, payload in decoded:
                if kind == "entry":
                    reply = sm.apply(payload.idx, payload.data)
                    epdb.note_applied(payload.clt_id, payload.req_id,
                                      payload.idx, reply)
                    nxt = payload.idx + 1
                else:
                    snap, ep_dump = payload
                    if kind == "snapfile":
                        sidecar = os.path.join(
                            os.path.dirname(self.store.path) or ".",
                            snap.data_path)
                        # Never adopt: the sidecar must survive for the
                        # NEXT restart too (the SM copies chunk-wise).
                        sm.apply_snapshot_file(snap, sidecar, adopt=False)
                    else:
                        sm.apply_snapshot(snap)
                    epdb.load(ep_dump)
                    if node is not None:
                        from apus_tpu.core.segment import Reassembler
                        node._seg = Reassembler.load(snap.seg)
                    nxt = snap.last_idx + 1
        except OSError as e:
            # A snapfile record whose sidecar is missing/short (deleted
            # by hand, ENOSPC'd copy): same policy — quarantine, reset
            # what the partial apply primed, start empty.
            if self.logger is not None:
                self.logger.error("store replay failed mid-apply: %s", e)
            self.quarantine()
            # Replay starts at the last snapshot record, so the only
            # state a mid-apply failure can leave behind is that
            # snapshot's partial prime — reset it (epdb is only loaded
            # after a successful apply, so it is still clean).
            try:
                from apus_tpu.models.sm import Snapshot as _Snap
                sm.apply_snapshot(_Snap(0, 0, b""))
            except Exception:               # noqa: BLE001
                pass
            return 1
        return nxt

    def close(self) -> None:
        self.store.close()


def decode_record(rec: bytes):
    """-> ("entry", LogEntry) | ("snapshot", (Snapshot, ep_dump))
    | ("snapfile", (Snapshot-with-data_path=sidecar-name, ep_dump))."""
    magic = rec[:4]
    if magic == RECORD_MAGIC:
        return "entry", wire.decode_entry(wire.Reader(rec[4:]))
    if magic == SNAP_MAGIC:
        last_idx, last_term = struct.unpack_from("<QQ", rec, 4)
        r = wire.Reader(rec[20:])
        data = r.blob()
        ep_dump = wire.decode_ep_dump(r)
        seg = r.blob() if r.remaining else b""
        fence = r.blob() if r.remaining else b""
        return "snapshot", (Snapshot(last_idx, last_term, data, seg=seg,
                                     fence=fence),
                            ep_dump)
    if magic == SNAPFILE_MAGIC:
        last_idx, last_term, data_len = struct.unpack_from("<QQQ", rec, 4)
        r = wire.Reader(rec[28:])
        name = r.blob().decode()
        ep_dump = wire.decode_ep_dump(r)
        seg = r.blob() if r.remaining else b""
        fence = r.blob() if r.remaining else b""
        return "snapfile", (Snapshot(last_idx, last_term, b"", seg=seg,
                                     fence=fence,
                                     data_path=name, data_len=data_len),
                            ep_dump)
    raise ValueError(
        f"unsupported store record format {magic!r} (expected "
        f"{RECORD_MAGIC!r}, {SNAP_MAGIC!r} or {SNAPFILE_MAGIC!r}); "
        f"refusing to decode")


def daemon_store_path(db_dir: str, idx: int) -> str:
    os.makedirs(db_dir, exist_ok=True)
    return os.path.join(db_dir, f"apus_records.{idx}.db")
