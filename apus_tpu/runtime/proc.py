"""Process-per-replica deployment: the run.sh launcher, locally.

The reference deploys one server process per machine over ssh
(benchmarks/run.sh:23-31) — consensus never shares an address space
with another replica.  The thread-based LocalCluster/ProxiedCluster are
hermetic test rigs; THIS module is the deployment shape: every replica
is its own OS process (`python -m apus_tpu.runtime.daemon`), with its
own interpreter and GIL, its own durable store, its own bridge + app.
Multi-host deployment is the same CLI with the same config file on each
host; ProcCluster is the local N-process launcher (and the harness the
failover benchmarks use).

Because replicas no longer contend on one GIL, the timing envelope
tightens from the thread-cluster DEBUG values (hb=10 ms,
elect=150-400 ms; appcluster.PROXIED_SPEC) to the reference's
production envelope (hb=1 ms, elect=10-30 ms, nodes.local.cfg:22-37) —
PROC_SPEC below.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shlex
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional, Sequence

from apus_tpu.runtime.appcluster import free_port as _free_port
from apus_tpu.runtime.client import probe_status
from apus_tpu.utils.config import ClusterSpec

#: Production timing envelope (nodes.local.cfg:22-37): hb=1 ms,
#: elect=10-30 ms.  Viable here because each replica process owns its
#: interpreter — the tick thread is never starved by sibling replicas.
PROC_SPEC = ClusterSpec(hb_period=0.001, hb_timeout=0.010,
                        elect_low=0.010, elect_high=0.030)

#: Relaxed envelope for MESH-PLANE deployments on small boxes: the
#: bring-up (jax import + compile x N processes) monopolizes the host
#: for tens of seconds and would starve PROC_SPEC's 1 ms ticks into
#: election churn.  Shared by the mesh e2e tests and fuzz campaign so
#: both exercise the same deployable timing.
MESH_PROC_SPEC = ClusterSpec(hb_period=0.010, hb_timeout=0.060,
                             elect_low=0.150, elect_high=0.400)


def _repo_env() -> dict:
    """Child env with the repo root on PYTHONPATH (daemons AND the
    mesh coordinator must resolve apus_tpu identically)."""
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in [root, env.get("PYTHONPATH")] if p])
    return env


class ProcCluster:
    """N replica processes on this host (the run.sh:23-31 analog).

    ``app_argv=None`` runs bare consensus daemons (DARE mode: clients
    use ApusClient against the peer ports).  ``app_argv=[...]`` runs an
    unmodified app under interpose.so per replica (APUS mode), port
    appended run.sh-style; ``app_argv="toyserver"`` uses the bundled
    native toy KV server.
    """

    def __init__(self, n: int, app_argv: Optional[Sequence[str] | str] = None,
                 workdir: Optional[str] = None,
                 spec: Optional[ClusterSpec] = None,
                 db: bool = True,
                 spin_timeout_ms: int = 8000,
                 tick_interval: Optional[float] = None,
                 device_plane: bool = False,
                 mesh_depth: int = 4,
                 follower_reads: Optional[bool] = None,
                 fault_plane: bool = False,
                 fault_seed: int = 0,
                 extra_env: Optional[dict] = None,
                 serve: bool = False):
        self.n = n
        #: per-replica extra environment for spawn/restart (slot ->
        #: {var: value}); chaos campaigns schedule disk faults by
        #: setting APUS_DISKFAULT_* here before a (re)start and
        #: clearing it afterwards (utils.store.FaultStore knobs).
        self.extra_env: dict[int, dict] = dict(extra_env or {})
        self.workdir = workdir or tempfile.mkdtemp(prefix="apus-proc-")
        os.makedirs(self.workdir, exist_ok=True)
        base = dataclasses.replace(spec or PROC_SPEC)
        if follower_reads is not None:
            base.follower_reads = follower_reads
        if fault_plane:
            # Live-stack fault plane on every daemon (parallel.faults):
            # tests script drops/partitions into the running processes
            # over the wire (faults.send_fault / isolate / heal_all).
            base.fault_plane = True
            base.fault_seed = fault_seed
        base.group_size = n
        base.peers = [f"127.0.0.1:{_free_port()}" for _ in range(n)]
        if device_plane:
            # Multi-controller mesh plane: each replica process owns one
            # device of a jax.distributed CPU mesh (runtime.mesh_plane);
            # replica 0 hosts the coordination service.
            base.mesh_coordinator = f"127.0.0.1:{_free_port()}"
            base.mesh_n = n
            base.mesh_depth = mesh_depth
            base.mesh_platform = "cpu"
        self.device_plane = device_plane
        self.spec = base
        self.config_path = os.path.join(self.workdir, "cluster.json")
        with open(self.config_path, "w") as f:
            json.dump(dataclasses.asdict(base), f, indent=1)

        if app_argv == "toyserver":
            from apus_tpu.runtime.appcluster import TOYSERVER, build_native
            build_native()
            app_argv = [TOYSERVER]
        self._app_argv = (list(app_argv)
                          if app_argv is not None else None)
        self._spin_timeout_ms = spin_timeout_ms
        self._tick_interval = tick_interval
        self._db = db
        self.app_ports: list[Optional[int]] = [
            _free_port() if app_argv is not None else None
            for _ in range(n)]
        #: Per-replica protocol-aware app gateway (runtime/serve.py;
        #: --serve-port): RESP/memcached-text app traffic served from
        #: the replicated KVS, opaque relay to the interposed app as
        #: the fallback.
        self.serve_ports: list[Optional[int]] = [
            _free_port() if serve else None for _ in range(n)]
        self.procs: list[Optional[subprocess.Popen]] = [None] * n
        #: replicas currently SIGSTOPped by the pause nemesis (resumed
        #: before teardown so SIGTERM is deliverable).
        self._paused: set[int] = set()
        self._logs: list = [None] * n
        self._coord: Optional[subprocess.Popen] = None
        self._coord_log = None

    # -- lifecycle --------------------------------------------------------

    def start(self, timeout: float = 30.0) -> None:
        # Port allocation is bind-then-close (_free_port): a child can
        # lose the EADDRINUSE race against an unrelated process.  One
        # full retry with fresh ports covers that rare loss.
        for attempt in (0, 1):
            try:
                if self.device_plane:
                    # Fresh coordinator address on EVERY cluster start:
                    # each start is a new mesh epoch, so daemons'
                    # per-incarnation markers (daemon._mesh_incarnation_
                    # fresh) never suppress a legitimately fresh mesh.
                    self.spec.mesh_coordinator = \
                        f"127.0.0.1:{_free_port()}"
                    with open(self.config_path, "w") as f:
                        json.dump(dataclasses.asdict(self.spec), f,
                                  indent=1)
                    self._spawn_coordinator()
                for i in range(self.n):
                    self._spawn(i)
                deadline = time.monotonic() + timeout
                for i in range(self.n):
                    self._wait_ready(i, deadline)
                for i in range(self.n):
                    self._wait_app(i, deadline)
                return
            except AssertionError:
                if attempt == 1:
                    raise
                self.stop()
                self.spec.peers = [f"127.0.0.1:{_free_port()}"
                                   for _ in range(self.n)]
                if self.device_plane:
                    self.spec.mesh_coordinator = \
                        f"127.0.0.1:{_free_port()}"
                self.app_ports = [
                    _free_port() if self._app_argv is not None else None
                    for _ in range(self.n)]
                with open(self.config_path, "w") as f:
                    json.dump(dataclasses.asdict(self.spec), f, indent=1)

    def _wait_app(self, i: int, deadline: float) -> None:
        """Block until replica i's app (launched by the daemon process)
        accepts connections."""
        import socket
        if self.app_ports[i] is None:
            return
        while time.monotonic() < deadline:
            p = self.procs[i]
            if p is not None and p.poll() is not None:
                raise AssertionError(
                    f"replica process {i} died while its app was "
                    f"starting (see {self.workdir}/proc{i}.out)")
            try:
                with socket.create_connection(
                        ("127.0.0.1", self.app_ports[i]), timeout=0.5):
                    return
            except OSError:
                time.sleep(0.05)
        raise AssertionError(f"app of replica {i} did not come up")

    def _spawn(self, i: int, join: bool = False) -> None:
        tag = f"join{i}" if join else str(i)
        argv = [sys.executable, "-m", "apus_tpu.runtime.daemon",
                "--config", self.config_path,
                "--log-file", os.path.join(self.workdir, f"srv{tag}.log"),
                "--ready-file", self._ready_path(i)]
        argv += ["--join"] if join else ["--idx", str(i)]
        if self._tick_interval is not None:
            argv += ["--tick-interval", str(self._tick_interval)]
        if self._db:
            argv += ["--db-dir", os.path.join(self.workdir, "db")]
        if self._app_argv is not None:
            argv += ["--workdir", self.workdir,
                     "--app", shlex.join(self._app_argv),
                     "--app-port", str(self.app_ports[i]),
                     "--spin-timeout-ms", str(self._spin_timeout_ms)]
        if self.serve_ports[i] is not None:
            argv += ["--serve-port", str(self.serve_ports[i])]
        if self._logs[i] is None:
            self._logs[i] = open(
                os.path.join(self.workdir, f"proc{tag}.out"), "ab")
        env = _repo_env()
        env.update({k: str(v)
                    for k, v in self.extra_env.get(i, {}).items()})
        # Orphan watchdog: if THIS harness process dies without stop()
        # (timeout-killed by a parent), the daemon self-exits when its
        # parent is no longer this pid (daemon.py main loop) — the pid
        # in the var (not a flag) closes the spawn-time race where the
        # harness dies before the child reaches its watchdog init.
        env["APUS_EXIT_IF_ORPHANED"] = str(os.getpid())
        # A stale ready file (unclean previous run in a reused workdir,
        # or a restart) would make _wait_ready return before the daemon
        # is actually up.
        try:
            os.unlink(self._ready_path(i))
        except OSError:
            pass
        # One process group per replica: kill() takes down the daemon
        # AND its app child in one signal, like a machine crash.
        self.procs[i] = subprocess.Popen(
            argv, env=env, stdout=self._logs[i], stderr=subprocess.STDOUT,
            start_new_session=True)

    def _spawn_coordinator(self) -> None:
        """The mesh coordination service in its OWN process — outside
        every replica, so fault injection on members can never trip the
        runtime's fatal coordinator-unreachable path (mesh_plane.
        serve_coordinator docstring)."""
        self._stop_coordinator()
        if self._coord_log is None:
            self._coord_log = open(
                os.path.join(self.workdir, "coordinator.out"), "ab")
        env = _repo_env()
        env["APUS_EXIT_IF_ORPHANED"] = str(os.getpid())  # see _spawn
        self._coord = subprocess.Popen(
            [sys.executable, "-m", "apus_tpu.runtime.mesh_plane",
             "--serve-coordinator", self.spec.mesh_coordinator,
             "--n", str(self.n)],
            env=env, stdout=self._coord_log, stderr=subprocess.STDOUT,
            start_new_session=True)

    def _stop_coordinator(self) -> None:
        if self._coord is not None and self._coord.poll() is None:
            self._coord.terminate()
            try:
                self._coord.wait(timeout=3.0)
            except subprocess.TimeoutExpired:
                self._coord.kill()
        self._coord = None

    def _ready_path(self, i: int) -> str:
        return os.path.join(self.workdir, f"ready{i}.json")

    def _wait_ready(self, i: int, deadline: float) -> dict:
        path = self._ready_path(i)
        while time.monotonic() < deadline:
            p = self.procs[i]
            if p is not None and p.poll() is not None:
                raise AssertionError(
                    f"replica process {i} exited rc={p.returncode} "
                    f"before READY (see {self.workdir}/proc{i}.out)")
            if os.path.exists(path):
                with open(path) as f:
                    return json.load(f)
            time.sleep(0.02)
        raise AssertionError(f"replica process {i} not ready in time")

    def stop(self) -> None:
        for i in list(self._paused):
            self.resume(i)          # SIGTERM pends on stopped processes
        for i, p in enumerate(self.procs):
            if p is not None and p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGTERM)
                except (OSError, ProcessLookupError):
                    p.terminate()
        for i, p in enumerate(self.procs):
            if p is None:
                continue
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    p.kill()
                p.wait(timeout=3.0)
            self.procs[i] = None
        self._stop_coordinator()
        for i, f in enumerate(self._logs):
            if f is not None:
                f.close()
                self._logs[i] = None
        if self._coord_log is not None:
            self._coord_log.close()
            self._coord_log = None

    def __enter__(self) -> "ProcCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- fault injection --------------------------------------------------

    def pause(self, idx: int) -> bool:
        """SIGSTOP replica ``idx``'s whole process group — the GC-pause
        /VM-freeze stand-in that historically kills lease systems: the
        process stops dead mid-whatever (lease checks included) while
        real time, its peers, and CLOCK_MONOTONIC keep running.  On
        resume the replica must observe its leases expired and refuse
        to serve — the adversarial-time nemesis pauses a lease-holding
        follower past expiry, commits newer writes, resumes it, and
        lets the audit plane judge what it serves."""
        p = self.procs[idx]
        if p is None or p.poll() is not None:
            return False
        try:
            os.killpg(p.pid, signal.SIGSTOP)
        except (OSError, ProcessLookupError):
            return False
        self._paused.add(idx)
        return True

    def resume(self, idx: int) -> None:
        """SIGCONT a paused replica (see pause)."""
        p = self.procs[idx]
        if p is not None:
            try:
                os.killpg(p.pid, signal.SIGCONT)
            except (OSError, ProcessLookupError):
                pass
        self._paused.discard(idx)

    def kill(self, idx: int) -> None:
        """Machine-crash a replica: SIGKILL its whole process group
        (daemon + app), no shutdown handshake (reconf_bench.sh:100-117)."""
        p = self.procs[idx]
        self._paused.discard(idx)   # SIGKILL works on stopped processes
        if p is None:
            return
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            p.kill()
        try:
            p.wait(timeout=3.0)
        except subprocess.TimeoutExpired:
            pass
        self.procs[idx] = None
        try:
            os.unlink(self._ready_path(idx))
        except OSError:
            pass

    def restart(self, idx: int, timeout: float = 30.0) -> dict:
        """Restart a killed replica at its original endpoint (durable
        store replay + catch-up)."""
        assert self.procs[idx] is None, "kill before restart"
        self._spawn(idx)
        return self._wait_ready(idx, time.monotonic() + timeout)

    def add_replica(self, timeout: float = 30.0) -> int:
        """Grow the group: spawn a NEW process that runs the join
        protocol against the current leader (`--join`; the AddServer /
        Upsize scenario, reconf_bench.sh:147-180).  Returns the slot the
        leader assigned."""
        i = len(self.procs)
        self.procs.append(None)
        self.app_ports.append(
            _free_port() if self._app_argv is not None else None)
        self.serve_ports.append(
            _free_port() if any(p is not None
                                for p in self.serve_ports) else None)
        self._logs.append(None)
        self._spawn(i, join=True)
        ready = self._wait_ready(i, time.monotonic() + timeout)
        slot = ready["idx"]
        # Mirror the joiner's endpoint into our local peer view (live
        # members learned it from the replicated CONFIG entry).
        while len(self.spec.peers) <= slot:
            self.spec.peers.append("")
        self.spec.peers[slot] = ready["addr"]
        if slot != i:
            # Slot reuse (joiner filled a removed member's slot): keep
            # proc bookkeeping aligned with slots.
            self.procs[slot], self.procs[i] = self.procs[i], None
            self.app_ports[slot] = self.app_ports[i]
            self.serve_ports[slot] = self.serve_ports[i]
        # Trim the trailing placeholder a slot-reusing join leaves
        # behind — a permanent None tail would make every "all slots
        # live" gate (failover/churn pacing) false forever.  Closing
        # the parent's log handle is safe: the child owns its own fd.
        while self.procs and self.procs[-1] is None \
                and len(self.procs) > len(self.spec.peers):
            self.procs.pop()
            self.app_ports.pop()
            self.serve_ports.pop()
            f = self._logs.pop()
            if f is not None:
                f.close()
        return slot

    def graceful_leave(self, idx: int, timeout: float = 30.0) -> None:
        """Operator-initiated graceful removal of replica ``idx``
        (OP_LEAVE, runtime.membership.request_leave): the leader
        commits the removal CONFIG entry, the drained daemon stops
        voting/serving and EXITS CLEAN — rc 0 is asserted here, the
        contract that separates a drain from a crash.  The freed slot
        is re-admittable via add_replica (next incarnation, snapshot
        catch-up)."""
        from apus_tpu.runtime.membership import request_leave
        peers = [p for i, p in enumerate(self.spec.peers)
                 if p and i != idx and i < len(self.procs)
                 and self.procs[i] is not None]
        # Elastic groups: the removal must commit in EVERY LIVE group,
        # including split-born ones beyond the static config — learn
        # the live count over the wire (a group the leave misses keeps
        # a dead member on its quorum floor forever).
        groups = getattr(self.spec, "groups", 1)
        for p in peers:
            st = probe_status(p, timeout=1.0)
            if st is not None:
                groups = max(groups, st.get("n_groups", 1))
                break
        request_leave(peers, idx, timeout=timeout,
                      victim_addr=self.spec.peers[idx],
                      groups=groups)
        p = self.procs[idx]
        if p is not None:
            try:
                rc = p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                raise AssertionError(
                    f"drained replica {idx} did not exit within "
                    f"{timeout}s (see {self.workdir}/proc{idx}.out)")
            assert rc == 0, \
                f"drained replica {idx} exited rc={rc} (clean exit is 0)"
            self.procs[idx] = None
            try:
                os.unlink(self._ready_path(idx))
            except OSError:
                pass

    # -- queries ----------------------------------------------------------

    def store_path(self, idx: int) -> str:
        """Replica ``idx``'s durable store file (db=True clusters) —
        chaos campaigns corrupt it by surgery while the process is
        killed, then exercise the restart recovery branches."""
        from apus_tpu.runtime.persist import daemon_store_path
        return daemon_store_path(os.path.join(self.workdir, "db"), idx)

    def status(self, idx: int, timeout: float = 0.5) -> Optional[dict]:
        return probe_status(self.spec.peers[idx], timeout=timeout)

    def leader_idx(self, timeout: float = 15.0) -> int:
        """Index of the (single) live leader, polled over the wire."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = []
            for i in range(len(self.spec.peers)):
                if self.procs[i] is None:
                    continue
                st = self.status(i, timeout=0.3)
                if st is not None and st.get("is_leader"):
                    leaders.append((i, st["term"]))
            if len(leaders) == 1:
                return leaders[0][0]
            if len(leaders) > 1:
                # Two banners can coexist briefly across a term bump;
                # trust the higher term only once it is unique.
                time.sleep(0.01)
                continue
            time.sleep(0.01)
        raise AssertionError("no stable leader within timeout")

    def app_addr(self, idx: int) -> tuple[str, int]:
        assert self.app_ports[idx] is not None
        return ("127.0.0.1", self.app_ports[idx])

    def serve_addr(self, idx: int) -> tuple[str, int]:
        """Replica ``idx``'s protocol-aware app gateway endpoint
        (constructed with serve=True)."""
        assert self.serve_ports[idx] is not None
        return ("127.0.0.1", self.serve_ports[idx])

    def wait_converged(self, timeout: float = 30.0,
                       idxs: Optional[list[int]] = None) -> None:
        """Block until every live replica's apply has reached the
        leader's commit (and something real committed).  The one wire-
        visible convergence criterion, shared by tests and fault
        campaigns instead of each hand-rolling the status poll."""
        want = idxs if idxs is not None else [
            i for i in range(len(self.spec.peers))
            if self.procs[i] is not None]
        deadline = time.monotonic() + timeout
        sts: list = []
        while time.monotonic() < deadline:
            sts = [self.status(i) for i in want]
            try:
                # Short leader probe, retried by THIS loop: an election
                # in flight is a transient, not a convergence failure.
                lead = self.status(self.leader_idx(timeout=1.0))
            except AssertionError:
                lead = None
            if all(s is not None for s in sts) and lead is not None \
                    and all(s["apply"] >= lead["commit"] > 1
                            for s in sts):
                return
            time.sleep(0.05)
        raise AssertionError(f"replicas did not converge: {sts}")

    def wait_config_converged(self, timeout: float = 30.0) -> dict:
        """Block until every LIVE replica reports the SAME STABLE
        configuration with no membership change in flight (cid epoch /
        state / bitmask equal across members, mid_resize false, no
        snapshot push outstanding) — the single-agreed-config
        convergence criterion of the churn nemesis, asserted through
        the OP_STATUS reconfiguration fields instead of log-scraping.
        Returns the agreed view."""
        deadline = time.monotonic() + timeout
        last: list = []
        while time.monotonic() < deadline:
            want = [i for i in range(len(self.procs))
                    if self.procs[i] is not None]
            sts = [self.status(i) for i in want]
            last = [(s or {}).get("epoch") for s in sts]
            if want and all(s is not None for s in sts):
                views = {(s.get("epoch"), s.get("cid_state"),
                          s.get("cid_bitmask"), s.get("group_size"))
                         for s in sts}
                live_mask = sum(1 << i for i in want)
                if len(views) == 1:
                    epoch, state, mask, size = next(iter(views))
                    if (state == "STABLE" and mask is not None
                            and not any(s.get("mid_resize")
                                        for s in sts)
                            and not any(s.get("snap_pushing")
                                        for s in sts)
                            and mask == live_mask):
                        return {"epoch": epoch, "cid_state": state,
                                "cid_bitmask": mask,
                                "group_size": size}
            time.sleep(0.05)
        raise AssertionError(
            f"configurations did not converge within {timeout}s: "
            f"epochs={last}")

    def wait_mesh_ready(self, timeout: float = 120.0,
                        tolerate_dead: bool = False) -> list:
        """Block until every live replica's mesh plane reports ready
        (the bring-up rendezvous — compile + gloo clique — finished).
        The ONE shared readiness criterion: tests/benches used to
        hand-roll subtly different status polls.  Returns the final
        per-replica devplane dicts.  A plane that died during bring-up
        raises unless ``tolerate_dead`` (callers that measure
        degradation semantics pass True and inspect the result).
        Leader probes are deliberately NOT part of the criterion:
        election churn while N JAX runtimes compile on a small box is
        expected and irrelevant to plane readiness."""
        deadline = time.monotonic() + timeout
        last: list = []
        while time.monotonic() < deadline:
            sts = [self.status(i, timeout=1.0)
                   for i in range(len(self.spec.peers))
                   if self.procs[i] is not None]
            last = [(s or {}).get("devplane") for s in sts]
            dead = [d for d in last if d and d.get("dead")]
            if dead:
                if tolerate_dead:
                    return last
                raise AssertionError(f"mesh died during bring-up: "
                                     f"{dead[0]}")
            if last and all(d and d.get("ready") for d in last):
                return last
            time.sleep(0.5)
        raise AssertionError(f"mesh plane never ready: {last}")

    def measure_failover(self, timeout: float = 15.0) -> float:
        """Kill the current leader and return seconds until a NEW leader
        is elected and answering status (reconf_bench.sh leader-failure
        scenario).  With PROC_SPEC this lands in the tens of
        milliseconds — the envelope the reference achieves with hb=1 ms
        / elect=10-30 ms."""
        victim = self.leader_idx()
        t0 = time.monotonic()
        self.kill(victim)
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            for i in range(len(self.spec.peers)):
                if i == victim or self.procs[i] is None:
                    continue
                st = self.status(i, timeout=0.2)
                if st is not None and st.get("is_leader"):
                    return time.monotonic() - t0
            time.sleep(0.002)
        raise AssertionError("no new leader after killing the old one")


def main(argv: Optional[list] = None) -> int:
    """`python -m apus_tpu.runtime.proc`: bring up N replica processes,
    print status, and keep running until Ctrl-C (a local stand-in for
    the reference's ssh fan-out in run.sh)."""
    import argparse

    ap = argparse.ArgumentParser(prog="python -m apus_tpu.runtime.proc")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--app", default=None,
                    help='app argv, or "toyserver" for the bundled one')
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)

    app = args.app
    if app is not None and app != "toyserver":
        app = shlex.split(app)
    pc = ProcCluster(args.replicas, app_argv=app, workdir=args.workdir)
    pc.start()
    try:
        leader = pc.leader_idx()
        print(f"cluster up: {args.replicas} replica processes, "
              f"leader={leader}, workdir={pc.workdir}")
        for i in range(args.replicas):
            print(f"  replica {i}: peer={pc.spec.peers[i]} "
                  f"app_port={pc.app_ports[i]} "
                  f"pid={pc.procs[i].pid if pc.procs[i] else None}")
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        return 0
    finally:
        pc.stop()


if __name__ == "__main__":
    sys.exit(main())
