"""Key -> consensus-group router (Multi-Raft client side).

The keyspace is sharded across ``spec.groups`` independent consensus
groups by a STABLE hash of the key bytes: every client (and every
harness, in every process, on every run) must route a given key to the
same group, or exactly-once breaks — the per-group endpoint DBs dedup
(clt_id, req_id) pairs, so a retry that hopped groups would re-execute.
CRC32 is stable across Python versions/processes (zlib), cheap, and
well-mixed enough after the golden-ratio spread for small group counts.

Contract (pinned by tests/test_multigroup.py):
- ``group_of_key(key, 1) == 0`` for every key (single-group routing is
  the identity — zero-cost back-compat);
- deterministic: same key, same group count -> same group, forever
  (changing this function is a WIRE-LEVEL compatibility break for any
  deployment with persisted multi-group state);
- all groups reachable (the test pins a coverage distribution).
"""

from __future__ import annotations

import struct
import zlib

#: 32-bit golden-ratio multiplier: spreads CRC32's low-bit structure
#: before the modulo so tiny group counts still see all groups.
_SPREAD = 0x9E3779B1
_MASK = 0xFFFFFFFF

#: Elastic routing granularity: the keyspace is quantized into this
#: many fixed BUCKETS (hash slots); a shard map assigns each bucket to
#: a consensus group and SPLIT/MERGE migrations move whole buckets.
#: 840 = lcm(1..8), so the INITIAL assignment ``bucket % n`` composes
#: to exactly ``group_of_key(key, n)`` for every genesis group count
#: the benches use — a cluster that never migrates routes identically
#: to the pre-elastic (pinned) hash, at every layer.
NBUCKETS = 840


def group_of_key(key: bytes, groups: int) -> int:
    """Stable key -> group id in [0, groups)."""
    if groups <= 1:
        return 0
    h = (zlib.crc32(key) * _SPREAD) & _MASK
    return (h >> 16) % groups


def bucket_of_key(key: bytes) -> int:
    """Stable key -> hash bucket in [0, NBUCKETS) — the migration unit
    of the elastic-group plane (same spread hash as group_of_key)."""
    h = (zlib.crc32(key) * _SPREAD) & _MASK
    return (h >> 16) % NBUCKETS


class ShardMap:
    """Versioned bucket -> group assignment (the client router's "hash
    epoch").  ``epoch`` bumps on every committed migration; a server
    answering a stale-epoch op sends the whole map back with the typed
    WRONG_GROUP hint, so one bounce re-synchronizes the client.
    Immutable; ``move`` returns a new map."""

    __slots__ = ("epoch", "assign")

    def __init__(self, epoch: int, assign: "tuple[int, ...]"):
        assert len(assign) == NBUCKETS, len(assign)
        self.epoch = epoch
        self.assign = tuple(assign)

    @staticmethod
    def initial(n_groups: int) -> "ShardMap":
        n = max(1, n_groups)
        return ShardMap(0, tuple(b % n for b in range(NBUCKETS)))

    @property
    def n_groups(self) -> int:
        return max(self.assign) + 1

    def group_of_key(self, key: bytes) -> int:
        return self.assign[bucket_of_key(key)]

    def owner(self, bucket: int) -> int:
        return self.assign[bucket]

    def owned(self, gid: int) -> "list[int]":
        return [b for b, g in enumerate(self.assign) if g == gid]

    def move(self, buckets, dst_gid: int, epoch: int) -> "ShardMap":
        assign = list(self.assign)
        for b in buckets:
            assign[b] = dst_gid
        return ShardMap(max(self.epoch, epoch), tuple(assign))

    @staticmethod
    def split_buckets(owned: "list[int]") -> "list[int]":
        """The half of ``owned`` a SPLIT ships to the new group
        (alternating, so a skewed contiguous hot range splits too)."""
        return sorted(owned)[1::2]

    # -- wire form (WRONG_GROUP hints, OP_SHARDMAP) ------------------------

    def to_blob(self) -> bytes:
        return (struct.pack("<IH", self.epoch, NBUCKETS)
                + bytes(self.assign))

    @staticmethod
    def from_blob(blob: bytes) -> "ShardMap":
        epoch, n = struct.unpack_from("<IH", blob)
        if n != NBUCKETS or len(blob) < 6 + n:
            raise ValueError(f"bad shard-map blob (n={n})")
        return ShardMap(epoch, tuple(blob[6:6 + n]))
