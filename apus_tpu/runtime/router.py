"""Key -> consensus-group router (Multi-Raft client side).

The keyspace is sharded across ``spec.groups`` independent consensus
groups by a STABLE hash of the key bytes: every client (and every
harness, in every process, on every run) must route a given key to the
same group, or exactly-once breaks — the per-group endpoint DBs dedup
(clt_id, req_id) pairs, so a retry that hopped groups would re-execute.
CRC32 is stable across Python versions/processes (zlib), cheap, and
well-mixed enough after the golden-ratio spread for small group counts.

Contract (pinned by tests/test_multigroup.py):
- ``group_of_key(key, 1) == 0`` for every key (single-group routing is
  the identity — zero-cost back-compat);
- deterministic: same key, same group count -> same group, forever
  (changing this function is a WIRE-LEVEL compatibility break for any
  deployment with persisted multi-group state);
- all groups reachable (the test pins a coverage distribution).
"""

from __future__ import annotations

import zlib

#: 32-bit golden-ratio multiplier: spreads CRC32's low-bit structure
#: before the modulo so tiny group counts still see all groups.
_SPREAD = 0x9E3779B1
_MASK = 0xFFFFFFFF


def group_of_key(key: bytes, groups: int) -> int:
    """Stable key -> group id in [0, groups)."""
    if groups <= 1:
        return 0
    h = (zlib.crc32(key) * _SPREAD) & _MASK
    return (h >> 16) % groups
