"""Segmentation layer — re-export of :mod:`apus_tpu.core.segment`.

The codec and reassembler live in ``core`` because the split/reassemble
points are inside the protocol node (submit and apply,
core.node); this module keeps the promised ``apus_tpu.runtime.segment``
name for runtime-level callers and docs.
"""

from apus_tpu.core.segment import *  # noqa: F401,F403 — tracks core.segment
