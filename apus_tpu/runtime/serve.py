"""Protocol-aware app serving surface: RESP + memcached text over the
replicated KVS.

The reference's deployment interposes UNMODIFIED apps and replicates
their byte streams (runtime/bridge.py — the opaque relay).  This
module is the serving mode BESIDE it: an :class:`AppServer` gateway
per replica terminates real app protocols (RESP for redis/SSDB
clients, memcached text) and maps the recognized command set straight
onto the replicated KVS through an ``ApusClient`` — which routes each
key to its consensus group (runtime/router.py), chases per-group
leaders for writes, and spreads GETs across replicas onto follower
read leases (linearizable; bucket-granular invalidation keeps a hot
writer from gating them).  Pipelined app clients coalesce: every
socket-read's worth of commands becomes ONE client pipeline call, so
app bursts ride the daemons' group-commit drain exactly like native
KVS bursts.

The OPAQUE RELAY REMAINS THE FALLBACK: the first command outside the
mapped set flips that connection to a transparent byte-stream proxy
against the replica's interposed app (when one is configured), whose
writes replicate through the capture path as before — so full app
semantics are never lost, only unaccelerated.  Without a fallback
backend the gateway answers a typed protocol error and keeps serving
the mapped set.

Mapped commands:

- RESP: GET SET DEL EXISTS INCR DECR MGET MSET PING ECHO SELECT QUIT
- memcached text: get (multi-key) set delete incr decr version quit
  (flags/exptime accepted and ignored — flags echo as 0; ``noreply``
  honored)

Protocol is sniffed per connection from the first bytes (``*`` =
RESP arrays; RESP inline commands and memcached text both parse as
words-on-a-line).
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Optional

from apus_tpu.models.kvs import (encode_delete, encode_get, encode_incr,
                                 encode_put)
from apus_tpu.obs.metrics import bump as _bump
from apus_tpu.runtime.client import OP_CLT_READ, OP_CLT_WRITE, ApusClient
from apus_tpu.runtime.overload import Overloaded

_NOT_NUM = b"!notint"


class AppServer:
    """One replica's protocol-aware app gateway (thread per
    connection; a per-connection ApusClient owns the KVS routing)."""

    def __init__(self, peers: "list[str]", host: str = "127.0.0.1",
                 port: int = 0, groups: int = 1,
                 fallback: "Optional[tuple[str, int]]" = None,
                 stats=None, logger: Optional[logging.Logger] = None,
                 client_timeout: float = 10.0):
        self.peers = list(peers)
        self.groups = max(1, groups)
        self.fallback = fallback
        self.stats = stats if stats is not None else {}
        self.logger = logger or logging.getLogger("apus.serve")
        self.client_timeout = client_timeout
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(512)
        self._lsock.settimeout(0.2)
        self.addr = self._lsock.getsockname()
        self._stop = threading.Event()
        self._threads: "list[threading.Thread]" = []

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(target=self._accept_loop,
                             name=f"apus-serve-{self.addr[1]}",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2.0)

    def __enter__(self) -> "AppServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            _bump(self.stats, "app_conns")
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="apus-serve-conn", daemon=True)
            t.start()
            self._threads.append(t)

    # -- per-connection loop -------------------------------------------

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(0.5)
        buf = b""
        proto = None          # sticky per-connection: "resp" | "mc"
        clt = ApusClient(list(self.peers), timeout=self.client_timeout,
                         groups=self.groups, read_policy="spread")
        try:
            while not self._stop.is_set():
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                # Parse every complete command in the buffer, then
                # execute the whole batch as ONE pipeline (app-client
                # pipelining -> one group-commit drain).
                cmds, buf, opaque_from = self._parse_all(buf, proto)
                if cmds and proto is None:
                    proto = cmds[0][0]
                if cmds:
                    replies, close = self._execute(clt, cmds)
                    if replies:
                        conn.sendall(b"".join(replies))
                    if close:
                        return
                if opaque_from is not None:
                    # Unrecognized command: the rest of this
                    # connection's life is the opaque relay (or a
                    # typed error when no backend is configured).
                    leftovers = opaque_from + buf
                    if self.fallback is not None:
                        _bump(self.stats, "app_fallback_conns")
                        self._relay(conn, leftovers)
                        return
                    _bump(self.stats, "app_errors")
                    kind = _sniff(leftovers)
                    conn.sendall(
                        b"-ERR unknown command (no relay backend)\r\n"
                        if kind == "resp" else b"ERROR\r\n")
                    buf = b""     # resync: drop the unparsed tail
        except OSError:
            return
        finally:
            clt.close()
            try:
                conn.close()
            except OSError:
                pass

    # -- parsing -------------------------------------------------------

    def _parse_all(self, buf: bytes, proto=None):
        """-> (commands, remaining_buf, opaque_from).  Each command is
        ("resp"|"mc", argv, extras...); opaque_from is the raw bytes of
        the first UNRECOGNIZED command (fallback takes over there).
        ``proto`` is the connection's sticky protocol once known —
        line commands on a RESP connection parse as RESP inline, never
        as memcached text."""
        cmds: list = []
        while buf:
            if buf[:1] == b"*" and proto in (None, "resp"):
                argv, used = _parse_resp(buf)
                if used == 0:
                    break
                if argv is None:
                    return cmds, buf, buf       # unparseable: opaque
                if not _resp_known(argv):
                    return cmds, buf[used:], buf[:used]
                cmds.append(("resp", argv))
                proto = "resp"
                buf = buf[used:]
                continue
            eol = buf.find(b"\r\n")
            nl = buf.find(b"\n")
            if eol < 0 and nl < 0:
                if len(buf) > (1 << 16):
                    return cmds, buf, buf       # runaway line: opaque
                break
            line_end = eol if 0 <= eol <= (nl if nl >= 0 else eol) \
                else nl
            line = buf[:line_end].rstrip(b"\r")
            consumed = line_end + (2 if line_end == eol else 1)
            words = line.split()
            if not words:
                buf = buf[consumed:]
                continue
            if proto == "resp":
                # RESP inline command on a RESP connection.
                if _resp_known(words) \
                        or _resp_known([w.upper() for w in words]):
                    cmds.append(("resp", words))
                    buf = buf[consumed:]
                    continue
                return cmds, buf[consumed:], buf[:consumed]
            w0 = words[0].lower()
            if w0 in (b"set", b"add") and len(words) >= 5:
                # memcached storage command: needs the data block.
                try:
                    nbytes = int(words[4])
                except ValueError:
                    return cmds, buf, buf
                noreply = len(words) >= 6 and words[5] == b"noreply"
                total = consumed + nbytes + 2
                if len(buf) < total:
                    break
                data = buf[consumed:consumed + nbytes]
                if w0 == b"add":
                    return cmds, buf[total:], buf[:total]
                cmds.append(("mc", words, data, noreply))
                buf = buf[total:]
                continue
            if w0 in (b"get", b"gets") and len(words) >= 2 \
                    and w0 == b"get":
                cmds.append(("mc", words, b"", False))
                buf = buf[consumed:]
                continue
            if w0 in (b"delete", b"incr", b"decr", b"version",
                      b"quit", b"stats"):
                noreply = words[-1] == b"noreply"
                cmds.append(("mc", words, b"", noreply))
                buf = buf[consumed:]
                continue
            # RESP inline command (PING etc. typed raw)?
            if _resp_known([w.upper() for w in words]) \
                    or _resp_known(words):
                cmds.append(("resp", words))
                buf = buf[consumed:]
                continue
            return cmds, buf[consumed:], buf[:consumed]
        return cmds, buf, None

    # -- execution -----------------------------------------------------

    def _execute(self, clt: ApusClient, cmds: list):
        """Run a parsed batch: KVS-mapped ops coalesce into ONE
        pipeline call; purely-local commands (PING, version...) answer
        in place.  Returns (replies in command order, close_conn)."""
        plan: list = []        # (reply-bytes-or-fn, close?) per command
        ops: list = []         # (op, data, gid) pipeline entries
        for c in cmds:
            if c[0] == "resp":
                plan.append(self._plan_resp(clt, c[1], ops))
            else:
                plan.append(self._plan_mc(clt, c[1], c[2], c[3], ops))
        try:
            results = clt.pipeline(ops) if ops else []
        except Overloaded:
            # Cluster shed the burst and the client's retry budget ran
            # dry: answer a typed protocol-native busy per pending
            # command instead of a silent stall.  Local commands
            # (PING, version...) still answer normally; memcached
            # ``noreply`` stays silent.
            _bump(self.stats, "app_busy_replies")
            out = []
            close = False
            for c, p in zip(cmds, plan):
                if callable(p[0]):
                    if not (c[0] == "mc" and c[3]):
                        out.append(b"-BUSY busy try again later\r\n"
                                   if c[0] == "resp"
                                   else b"SERVER_ERROR busy\r\n")
                elif p[0]:
                    out.append(p[0])
                if len(p) > 1 and p[1]:
                    close = True
                    break
            return out, close
        _bump(self.stats, "app_kvs_ops", len(ops))
        out: "list[bytes]" = []
        close = False
        for p in plan:
            r = p[0](results) if callable(p[0]) else p[0]
            if r:
                out.append(r)
            if len(p) > 1 and p[1]:
                close = True
                break
        return out, close

    # RESP command set we map; everything else falls back.
    _RESP_OK = {b"GET", b"SET", b"DEL", b"EXISTS", b"INCR", b"DECR",
                b"MGET", b"MSET", b"PING", b"ECHO", b"SELECT", b"QUIT"}

    def _plan_resp(self, clt, argv, ops):
        cmd = argv[0].upper()
        _bump(self.stats, "app_resp_cmds")
        if cmd == b"PING":
            _bump(self.stats, "app_local_cmds")
            return (b"+PONG\r\n",)
        if cmd == b"ECHO" and len(argv) == 2:
            _bump(self.stats, "app_local_cmds")
            return (b"$%d\r\n%s\r\n" % (len(argv[1]), argv[1]),)
        if cmd == b"SELECT":
            _bump(self.stats, "app_local_cmds")
            return (b"+OK\r\n",)
        if cmd == b"QUIT":
            return (b"+OK\r\n", True)
        if cmd == b"SET" and len(argv) == 3:
            i = self._push(clt, ops, OP_CLT_WRITE,
                           encode_put(argv[1], argv[2]), argv[1])
            return (lambda rs, i=i:
                    b"+OK\r\n" if rs[i] == b"OK"
                    else b"-ERR write failed\r\n",)
        if cmd == b"GET" and len(argv) == 2:
            i = self._push(clt, ops, OP_CLT_READ,
                           encode_get(argv[1]), argv[1])
            return (lambda rs, i=i: _resp_bulk(rs[i]),)
        if cmd == b"DEL" and len(argv) >= 2:
            idxs = [self._push(clt, ops, OP_CLT_WRITE,
                               encode_delete(k), k)
                    for k in argv[1:]]
            return (lambda rs, idxs=idxs:
                    b":%d\r\n" % sum(1 for i in idxs
                                     if rs[i] == b"OK"),)
        if cmd in (b"INCR", b"DECR") and len(argv) == 2:
            delta = 1 if cmd == b"INCR" else -1
            i = self._push(clt, ops, OP_CLT_WRITE,
                           encode_incr(argv[1], delta), argv[1])
            return (lambda rs, i=i:
                    (b"-ERR value is not an integer\r\n"
                     if rs[i] == _NOT_NUM
                     else b":%d\r\n" % int(rs[i])),)
        if cmd == b"MGET" and len(argv) >= 2:
            idxs = [self._push(clt, ops, OP_CLT_READ,
                               encode_get(k), k) for k in argv[1:]]
            return (lambda rs, idxs=idxs:
                    b"*%d\r\n" % len(idxs)
                    + b"".join(_resp_bulk(rs[i]) for i in idxs),)
        if cmd == b"MSET" and len(argv) >= 3 and len(argv) % 2 == 1:
            idxs = [self._push(clt, ops, OP_CLT_WRITE,
                               encode_put(argv[j], argv[j + 1]),
                               argv[j])
                    for j in range(1, len(argv), 2)]
            return (lambda rs, idxs=idxs: b"+OK\r\n",)
        if cmd == b"EXISTS" and len(argv) >= 2:
            idxs = [self._push(clt, ops, OP_CLT_READ,
                               encode_get(k), k) for k in argv[1:]]
            return (lambda rs, idxs=idxs:
                    b":%d\r\n" % sum(1 for i in idxs if rs[i]),)
        _bump(self.stats, "app_errors")
        return (b"-ERR wrong number of arguments\r\n",)

    def _plan_mc(self, clt, words, data, noreply, ops):
        cmd = words[0].lower()
        _bump(self.stats, "app_mc_cmds")
        if cmd == b"version":
            _bump(self.stats, "app_local_cmds")
            return (b"VERSION 1.4.21-apus\r\n",)
        if cmd == b"quit":
            return (b"", True)
        if cmd == b"stats":
            return (b"END\r\n",)
        if cmd == b"set":
            i = self._push(clt, ops, OP_CLT_WRITE,
                           encode_put(words[1], data), words[1])
            if noreply:
                return (lambda rs, i=i: b"",)
            return (lambda rs, i=i:
                    b"STORED\r\n" if rs[i] == b"OK"
                    else b"SERVER_ERROR write failed\r\n",)
        if cmd == b"get":
            keys = words[1:]
            idxs = [self._push(clt, ops, OP_CLT_READ, encode_get(k), k)
                    for k in keys]
            def fmt(rs, keys=keys, idxs=idxs):
                out = []
                for k, i in zip(keys, idxs):
                    v = rs[i]
                    if v:
                        out.append(b"VALUE %s 0 %d\r\n%s\r\n"
                                   % (k, len(v), v))
                out.append(b"END\r\n")
                return b"".join(out)
            return (fmt,)
        if cmd == b"delete" and len(words) >= 2:
            i = self._push(clt, ops, OP_CLT_READ,
                           encode_get(words[1]), words[1])
            j = self._push(clt, ops, OP_CLT_WRITE,
                           encode_delete(words[1]), words[1])
            if noreply:
                return (lambda rs: b"",)
            return (lambda rs, i=i, j=j:
                    b"DELETED\r\n" if rs[i] else b"NOT_FOUND\r\n",)
        if cmd in (b"incr", b"decr") and len(words) >= 3:
            try:
                delta = int(words[2])
            except ValueError:
                return (b"CLIENT_ERROR invalid numeric delta "
                        b"argument\r\n",)
            if cmd == b"decr":
                delta = -delta
            i = self._push(clt, ops, OP_CLT_WRITE,
                           encode_incr(words[1], delta), words[1])
            if noreply:
                return (lambda rs: b"",)
            return (lambda rs, i=i:
                    (b"CLIENT_ERROR cannot increment or decrement "
                     b"non-numeric value\r\n" if rs[i] == _NOT_NUM
                     else b"%d\r\n" % max(0, int(rs[i]))),)
        _bump(self.stats, "app_errors")
        return (b"ERROR\r\n",)

    def _push(self, clt: ApusClient, ops: list, op: int, data: bytes,
              key: bytes) -> int:
        ops.append((op, data, clt.group_of(key)))
        return len(ops) - 1

    # -- opaque relay fallback -----------------------------------------

    def _relay(self, conn: socket.socket, pending: bytes) -> None:
        """Transparent byte-stream proxy to the interposed app (the
        PR-13-and-earlier serving surface): everything this connection
        says from now on goes to the real app verbatim, and its
        replies come back verbatim.  The app side is interposed, so
        writes keep replicating through the capture path."""
        try:
            app = socket.create_connection(self.fallback, timeout=5.0)
        except OSError:
            _bump(self.stats, "app_errors")
            return
        app.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            if pending:
                app.sendall(pending)
            conn.settimeout(0.2)
            app.settimeout(0.2)
            import select as _select
            while not self._stop.is_set():
                r, _, _ = _select.select([conn, app], [], [], 0.2)
                for s in r:
                    try:
                        chunk = s.recv(1 << 16)
                    except socket.timeout:
                        continue
                    if not chunk:
                        return
                    _bump(self.stats, "app_fallback_bytes", len(chunk))
                    (app if s is conn else conn).sendall(chunk)
        except OSError:
            return
        finally:
            try:
                app.close()
            except OSError:
                pass


def _sniff(buf: bytes) -> str:
    return "resp" if buf[:1] == b"*" else "mc"


def _resp_bulk(v: "bytes | None") -> bytes:
    if not v:
        return b"$-1\r\n"
    return b"$%d\r\n%s\r\n" % (len(v), v)


def _resp_known(argv) -> bool:
    return bool(argv) and argv[0].upper() in AppServer._RESP_OK


def _parse_resp(buf: bytes):
    """One RESP array-of-bulk-strings command at the head of ``buf``
    -> (argv | None, bytes_used); (None, >0) = malformed, ( _, 0) =
    incomplete."""
    eol = buf.find(b"\r\n")
    if eol < 0:
        return None, 0
    try:
        n = int(buf[1:eol])
    except ValueError:
        return None, eol + 2
    off = eol + 2
    argv = []
    for _ in range(max(0, n)):
        if buf[off:off + 1] != b"$":
            return (None, off) if len(buf) > off else (None, 0)
        eol = buf.find(b"\r\n", off)
        if eol < 0:
            return None, 0
        try:
            blen = int(buf[off + 1:eol])
        except ValueError:
            return None, eol + 2
        start = eol + 2
        if len(buf) < start + blen + 2:
            return None, 0
        argv.append(buf[start:start + blen])
        off = start + blen + 2
    return argv, off
