"""Cross-group atomic transactions (2PC over the groups' own logs).

PR 12's second tentpole piece: the "Reconfigurable Atomic Transaction
Commit" discipline (PAPERS.md) made concrete — an atomic-commit
protocol whose EVERY decision lives in a replicated log, so it
survives the failure of whoever drove it, and whose every fence is a
config/shard-map epoch, so reconfiguration and a concurrent
SPLIT/MERGE mid-2PC abort or complete cleanly instead of wedging or
double-applying.

Protocol (records encoded in models/kvs.py; all idempotent by the
transaction id = the originating client's (clt_id, req_id)):

    TB  (coordinator group's log)   the durable intent: participant
        gids + each group's sub-ops, replicated BEFORE any prepare is
        sent — whoever comes to lead the coordinator group resumes the
        transaction (elastic.py-driver style; a coordinator SIGKILL
        between PREPARE and DECIDED just moves the driver).
    TP  (each participant group's log)   prepare: lock the keys
        (exclusive 2PL — write-locked keys refuse reads too), evaluate
        the sub-ops against the locked state and record replies +
        buffered writes.  Locks live in the SM, mirrored through
        snapshots/deltas/restart replay, so prepared state survives
        leader kills AND whole-quorum SIGKILLs.  Deterministic
        refusals (frozen/departed bucket, lock conflict) are
        REFUSED_TX-prefixed — never dedup-noted, passed through to the
        driver verbatim.
    TD  (coordinator group's log)   THE decision point: first TD in
        the coordinator log's order wins on every replica.  Submitted
        under the CLIENT's identity, so a commit's apply-time reply is
        epdb-noted exactly like a single op's — the whole cross-group
        transaction inherits exactly-once from the ordinary dedup
        machinery (aborts return a REFUSED sentinel, never noted; the
        client retries under a fresh req_id).
    TC/TA  (participant logs)   install the buffered writes / drop
        them; release the locks either way.  TA for an unknown txn
        records an aborted tombstone so a straggler TP from an
        abandoned driver attempt can never lock keys post-decision.
    TF  (coordinator log)   every participant acked its close — stop
        re-driving (tombstone, pruned).

Why split/merge cannot race a 2PC into a wedge or a double-apply: the
freeze record (MB) and the prepare (TP) serialize through the SAME
per-group log — MB defers (deterministic REFUSED, elastic driver
retries) while any write-locked key sits in its bucket set, and TP
refuses on frozen/departed buckets (the coordinator aborts and the
client retries against the fresh map).  Mutual exclusion through log
order, no cross-plane locks.

Client surface: ``ApusClient.txn([...])`` ships the whole sub-op list
to the coordinator (OP_TXN, a top-level op — the SERVER plans the
grouping against its own shard map).  Single-group transactions
bypass 2PC entirely: one TM log entry gives atomic visibility for
free from log order.  This is also the stated CROSS-GROUP alternative
to pipelined read-your-write, which remains a within-group contract
(DESIGN.md "Transactions & replicated data types").
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from typing import Optional

from apus_tpu.models.kvs import (REFUSED_TX, TXN_REPLY_MAGIC,
                                 _dec_subs, encode_txn_abort,
                                 encode_txn_begin, encode_txn_commit,
                                 encode_txn_decide, encode_txn_finish,
                                 encode_txn_multi, encode_txn_prepare,
                                 parse_txn_key, txn_key,
                                 unpack_replies)
from apus_tpu.parallel import wire

#: client op: submit a whole transaction (top-level — never
#: group-wrapped; the payload's keys decide the participant groups)
OP_TXN = 31

#: typed bounce: the transaction was DECIDED ABORT (deterministic —
#: nothing applied anywhere); the client retries under a fresh req_id
ST_TXN_ABORTED = 10


def encode_txn_subs(cmds) -> bytes:
    """Client-side sub-op list -> OP_TXN payload blob."""
    from apus_tpu.models.kvs import _enc_subs
    return _enc_subs(list(enumerate(cmds)))


def decode_txn_subs(blob: bytes) -> "list[bytes]":
    subs, _ = _dec_subs(blob, 0)
    return [c for _p, c in sorted(subs)]


def _is_read(cmd: bytes) -> bool:
    from apus_tpu.models.kvs import cmd_is_read
    return cmd_is_read(cmd)


class TxnPlane:
    """Per-daemon transaction plane: the OP_TXN service plus the
    recovery DRIVER — a watchdog thread that resumes any open
    coordinator transaction whose group this daemon currently leads
    (a coordinator kill mid-2PC moves the driver with the
    leadership; every step is idempotent)."""

    #: an open txn older than this (first seen by THIS driver) is
    #: adopted by the background pass — the inline fast path in the
    #: client handler normally resolves far sooner
    RESUME_AGE = 0.5
    #: an open txn the driver cannot collect prepares for within this
    #: window is decided ABORT (a dead participant group blocks only
    #: its own transactions, and only this long)
    ABORT_AGE = 8.0

    def __init__(self, daemon):
        self.daemon = daemon
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # PER-THREAD driver clients (the inline fast path runs on
        # per-connection server threads, the recovery driver on its
        # own): the endpoint-DB dedup is MONOTONE per client id, so
        # two concurrent transactions sharing one identity could have
        # a delayed prepare's apply deduped against the other's later
        # req — and answered with the WRONG reply (observed as
        # "badreply" aborts + wedged prepared participants before
        # this was per-thread).
        self._tl = threading.local()
        self._clts: list = []
        self._clts_lock = threading.Lock()
        # Driver-submitted records (TB/TF and the participant-side
        # TP/TC/TA) ride the normal client-write path under a
        # plane-owned identity; TD alone carries the CLIENT's identity
        # (see module docstring).
        self._sys_clt = secrets.randbits(62) | (1 << 61)
        self._sys_req = 0
        self._sys_lock = threading.Lock()
        #: tk -> first-seen monotonic (age for resume/abort decisions)
        self._seen: dict[str, float] = {}
        #: tks this plane instance BEGAN (an adopted one it didn't is
        #: a RESUMED txn — the mid-2PC takeover evidence)
        self._started: set[str] = set()
        #: tks currently being driven by some thread of this plane
        self._driving: set[str] = set()
        self._drv_lock = threading.Lock()
        # Nemesis window widener (benchmarks/fuzz.py --txn): hold the
        # 2PC between collected prepares and the decide record for
        # this many seconds, so a seeded coordinator SIGKILL lands
        # mid-2PC deterministically often.  0 (default) = off.
        try:
            self.prep_hold = float(
                os.environ.get("APUS_TXN_PREP_HOLD", "0") or 0)
        except ValueError:
            self.prep_hold = 0.0

    def _next_req(self) -> int:
        with self._sys_lock:
            self._sys_req += 1
            return self._sys_req

    # -- planning (under the daemon lock) -----------------------------------

    def plan(self, cmds: "list[bytes]"):
        """Sub-op commands -> ({gid: [(pos, cmd)]}, map_epoch), or
        None for an unroutable payload.  Grouping uses THIS daemon's
        derived shard map — the freshest view it can have; a stale
        grouping is caught by the participants' own fences (prepare
        refuses on departed/frozen) and aborts cleanly."""
        from apus_tpu.models.kvs import decode_key
        d = self.daemon
        shard = (d.elastic.shard_map() if d.elastic is not None
                 else None)
        groups: dict[int, list] = {}
        for pos, c in enumerate(cmds):
            key = decode_key(c)
            if key is None:
                return None
            if shard is not None:
                gid = shard.group_of_key(key)
            elif d.n_groups > 1:
                from apus_tpu.runtime.router import group_of_key
                gid = group_of_key(key, d.n_groups)
            else:
                gid = 0
            groups.setdefault(gid, []).append((pos, c))
        epoch = shard.epoch if shard is not None else 0
        return groups, epoch

    # -- observability -------------------------------------------------------

    def _tnote(self, msg: str, **fields) -> None:
        if self.daemon.obs is not None:
            self.daemon.obs.flight.note("txn", msg, **fields)

    def txns_view(self) -> dict:
        """OP_STATUS view: every unresolved transaction any local SM
        knows — open/decided coordinator records and prepared
        participant records with their lock counts (the failure dumps
        attach this beside the groups/router views).  Caller holds
        the daemon lock."""
        coord, prepared = [], []
        for gid, node in self._nodes():
            sm = node.sm
            for tk, rec in getattr(sm, "txns_coord", {}).items():
                if rec[0] != "done":
                    coord.append({"txn": tk, "gid": gid,
                                  "state": rec[0], "epoch": rec[1]})
            for tk, rec in getattr(sm, "txns_in", {}).items():
                if rec[2] == "prepared":
                    prepared.append({"txn": tk, "gid": gid,
                                     "coord": rec[0], "epoch": rec[1]})
        locks = sum(len(getattr(n.sm, "_locks", ()) or ())
                    for _g, n in self._nodes())
        return {"coord_open": coord, "prepared": prepared,
                "locked_keys": locks}

    def _nodes(self):
        d = self.daemon
        if d.groupset is not None:
            return list(enumerate(d.groupset.nodes))
        return [(0, d.node)]

    # -- recovery driver -----------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(target=self._run, daemon=True,
                             name=f"apus-txn-{self.daemon.idx}")
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        with self._clts_lock:
            clts, self._clts = self._clts, []
        for c in clts:
            try:
                c.close()
            except OSError:
                pass

    def _run(self) -> None:
        while not self._stop.wait(0.1):
            try:
                self._pass()
            except Exception:                     # noqa: BLE001
                self.daemon.logger.exception("txn driver pass failed")

    def _pass(self) -> None:
        """Adopt every unresolved coordinator transaction whose group
        this daemon currently leads."""
        d = self.daemon
        now = time.monotonic()
        work = []
        with d.lock:
            live = set()
            for gid, node in self._nodes():
                if not node.is_leader:
                    continue
                for tk, rec in getattr(node.sm, "txns_coord",
                                       {}).items():
                    if rec[0] == "done":
                        continue
                    live.add(tk)
                    first = self._seen.setdefault(tk, now)
                    if rec[0] != "open" \
                            or now - first >= self.RESUME_AGE:
                        work.append((gid, node, tk))
            for tk in [t for t in self._seen if t not in live]:
                self._seen.pop(tk, None)
                self._started.discard(tk)
        for gid, node, tk in work:
            if self._stop.is_set():
                return
            self.drive(tk, gid, node)

    # -- the 2PC drive (idempotent; inline fast path + recovery) ------------

    def drive(self, tk: str, gid: int, node) -> None:
        with self._drv_lock:
            if tk in self._driving:
                return
            self._driving.add(tk)
        if tk not in self._started:
            # Adopting a transaction THIS plane did not begin — the
            # mid-2PC takeover evidence (coordinator kill between
            # PREPARE and DECIDED; the new leader resumes it).
            node.bump("txn_resumed")
            self._tnote("resumed", txn=tk, gid=gid)
            self._started.add(tk)
        try:
            self._drive_txn(tk, gid, node)
        finally:
            with self._drv_lock:
                self._driving.discard(tk)

    def _drive_txn(self, tk: str, gid: int, node) -> None:
        d = self.daemon
        clt, req = parse_txn_key(tk)
        with d.lock:
            rec = node.sm.txns_coord.get(tk)
            if rec is None or rec[0] == "done":
                return
            state, epoch = rec[0], rec[1]
            groups = {int(g): _dec_subs(s.encode("latin-1"), 0)[0]
                      for g, s in json.loads(rec[2]).items()}
        obs = d.obs
        sp = obs.spans if obs is not None else None
        if state == "open":
            replies: dict[int, bytes] = {}
            outcome = True
            reason = b""
            if sp is not None and sp.sampled(req):
                sp.stamp(clt, req, "txn_prepare")
            for pgid in sorted(groups):
                resp = self._group_write(
                    pgid, encode_txn_prepare(clt, req, gid, epoch,
                                             groups[pgid]))
                if resp is None:
                    # Participant unreachable: retry on a later pass
                    # (its prepared state, if any, is idempotent) —
                    # abort only past the blocking window.
                    age = time.monotonic() - self._seen.get(
                        tk, time.monotonic())
                    if age < self.ABORT_AGE:
                        return
                    outcome, reason = False, b"unreachable"
                    break
                if resp.startswith(REFUSED_TX):
                    outcome = False
                    reason = resp[len(REFUSED_TX):]
                    break
                if not resp.startswith(TXN_REPLY_MAGIC):
                    outcome, reason = False, b"badreply"
                    break
                node.bump("txn_prepared")
                replies.update(dict(unpack_replies(resp)))
            if self.prep_hold:
                time.sleep(self.prep_hold)
            if not outcome:
                if reason == b"locked":
                    node.bump("txn_lock_conflicts")
                elif reason in (b"frozen", b"departed"):
                    node.bump("txn_epoch_aborts")
            from apus_tpu.models.kvs import pack_replies
            blob = pack_replies(sorted(replies.items())) if outcome \
                else b""
            # TD under the CLIENT's identity: apply notes the epdb for
            # (clt, req) with the assembled reply — exactly-once for
            # the whole transaction via the ordinary dedup machinery.
            with d.lock:
                if not node.is_leader:
                    return
                pr = node.submit(req, clt,
                                 encode_txn_decide(clt, req, outcome,
                                                   blob))
                if pr is None:
                    return
                node.flush_pending()
            deadline = time.monotonic() + 5.0
            with d.commit_cond:
                while pr.reply is None:
                    if not node.is_leader \
                            or time.monotonic() >= deadline:
                        return            # retried on a later pass
                    d.commit_cond.wait(0.25)
            node.bump("txn_decided" if outcome else "txn_aborted")
            if sp is not None and sp.sampled(req):
                sp.stamp(clt, req, "txn_decide")
            self._tnote("decided", txn=tk,
                       outcome="commit" if outcome else "abort",
                       reason=reason.decode("latin-1", "replace"))
            state = "committed" if outcome else "aborted"
        if state in ("committed", "aborted"):
            close = (encode_txn_commit if state == "committed"
                     else encode_txn_abort)
            for pgid in sorted(groups):
                if self._group_write(pgid, close(clt, req)) != b"OK":
                    return                # retried on a later pass
            with d.lock:
                if not node.is_leader:
                    return
                pr = node.submit(self._next_req(), self._sys_clt,
                                 encode_txn_finish(clt, req))
                if pr is not None:
                    node.flush_pending()
            self._tnote("closed", txn=tk, state=state)

    def _group_write(self, gid: int,
                     data: bytes) -> "bytes | None":
        """One replicated write into group ``gid`` through the
        ordinary client path (leader chase + exactly-once under the
        plane identity).  Returns the reply bytes — including
        REFUSED_TX-prefixed refusals, which the client service passes
        through verbatim — or None on timeout/unreachable."""
        from apus_tpu.runtime.client import OP_CLT_WRITE, ApusClient
        c = getattr(self._tl, "clt", None)
        if c is None:
            c = ApusClient([p for p in self.daemon.spec.peers if p],
                           clt_id=secrets.randbits(62) | (1 << 61),
                           timeout=6.0, attempt_timeout=2.0,
                           wrong_group_refuses=True)
            self._tl.clt = c
            with self._clts_lock:
                self._clts.append(c)
        try:
            c._req_seq += 1
            return c._op(OP_CLT_WRITE, c._req_seq, data, gid=gid)
        except RuntimeError as e:
            if "wrong_group" in str(e):
                # The record's target group no longer owns the keys (a
                # split/merge committed mid-2PC): a deterministic
                # epoch-fence refusal — the coordinator aborts and the
                # client replans against the fresh map.
                return REFUSED_TX + b"departed"
            return None
        except (TimeoutError, OSError, ConnectionError):
            return None


# -- daemon-side client op ---------------------------------------------------

def make_txn_ops(daemon) -> dict:
    from apus_tpu.models.sm import REFUSED_REPLY_PREFIX
    from apus_tpu.runtime.client import (ST_MIGRATING, ST_TIMEOUT,
                                         _elastic_bounce, _not_leader)

    plane = daemon.txn

    def clt_txn(r: wire.Reader) -> bytes:
        req_id, clt_id = r.u64(), r.u64()
        cmds = decode_txn_subs(r.blob())
        obs = daemon.obs
        sp = obs.spans if obs is not None else None
        traced = sp is not None and sp.sampled(req_id)
        if traced:
            sp.stamp(clt_id, req_id, "ingest")
        with daemon.lock:
            planned = plane.plan(cmds)
            if planned is None or not cmds:
                return wire.u8(wire.ST_ERROR) + wire.u64(req_id)
            groups, epoch = planned
            coord_gid = min(groups)
            node = daemon.group_node(coord_gid)
            if node is None or not node.is_leader:
                return _not_leader(daemon, req_id,
                                   node=node or daemon.node)
            if traced:
                sp.stamp(clt_id, req_id, "lock")
            el = daemon.elastic
            tk = txn_key(clt_id, req_id)
            dup = node.epdb.duplicate_of_applied(clt_id, req_id)
            if dup is not None and dup.last_req_id == req_id:
                return (wire.u8(wire.ST_OK) + wire.u64(req_id)
                        + wire.blob(dup.last_reply or b""))
            if len(groups) == 1:
                # WITHIN-GROUP fast path: one TM log entry, atomic
                # visibility from log order — no 2PC, no locks.
                data = encode_txn_multi(cmds)
                if el is not None and dup is None:
                    v = el.admit(node, data)
                    if v is not None:
                        return _elastic_bounce(daemon, node, req_id,
                                               v)
                pr = node.submit(req_id, clt_id, data)
                if pr is None:
                    return _not_leader(daemon, req_id, node=node)
                node.flush_pending()
                mode = "multi"
            else:
                # CROSS-GROUP: replicate the durable TB intent, then
                # drive the 2PC inline (the recovery driver adopts it
                # if this handler/daemon dies mid-protocol).
                if node.sm.txns_coord.get(tk) is None:
                    pr0 = node.submit(
                        plane._next_req(), plane._sys_clt,
                        encode_txn_begin(clt_id, req_id, epoch,
                                         groups))
                    if pr0 is None:
                        return _not_leader(daemon, req_id, node=node)
                    node.flush_pending()
                    plane._started.add(tk)
                    plane._seen.setdefault(tk, time.monotonic())
                    plane._tnote("begin", txn=tk, groups=len(groups))
                pr = None
                mode = "2pc"
        deadline = time.monotonic() + daemon.client_op_timeout
        if mode == "multi":
            node.bump("txn_batches")
            n_writes = sum(1 for c0 in cmds
                           if not _is_read(c0))
            with daemon.commit_cond:
                while True:
                    if pr.reply is not None:
                        if pr.reply.startswith(REFUSED_REPLY_PREFIX):
                            # Raced a leader change past an unapplied
                            # migration/lock record and no-op'd: typed
                            # bounce, exactly as the single-op path.
                            if daemon.elastic is not None:
                                from apus_tpu.runtime.client import \
                                    _sentinel_bounce
                                return _sentinel_bounce(
                                    daemon, node, req_id, cmds[0],
                                    pr.reply)
                            return (wire.u8(ST_MIGRATING)
                                    + wire.u64(req_id))
                        if traced:
                            sp.stamp(clt_id, req_id, "reply",
                                     idx=pr.idx)
                            sp.finish(clt_id, req_id)
                        # Same per-group write service-capacity gate
                        # as the single-op/batch paths (bench.py
                        # methodology) — a TM batch pays per write.
                        from apus_tpu.runtime.client import \
                            _wsvc_emulate
                        _wsvc_emulate(daemon, node.gid, n_writes)
                        return (wire.u8(wire.ST_OK) + wire.u64(req_id)
                                + wire.blob(pr.reply))
                    if not node.is_leader:
                        return _not_leader(daemon, req_id, node=node)
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return wire.u8(ST_TIMEOUT) + wire.u64(req_id)
                    daemon.commit_cond.wait(min(left, 0.25))
        # 2PC: wait for TB to apply, drive inline, then wait for the
        # decision (TD apply notes the epdb / flips the record state).
        with daemon.commit_cond:
            while node.sm.txns_coord.get(tk) is None:
                if not node.is_leader:
                    return _not_leader(daemon, req_id, node=node)
                if time.monotonic() >= deadline:
                    return wire.u8(ST_TIMEOUT) + wire.u64(req_id)
                daemon.commit_cond.wait(0.25)
        plane.drive(tk, coord_gid, node)
        with daemon.commit_cond:
            while True:
                rec = node.sm.txns_coord.get(tk)
                if rec is not None:
                    if rec[0] in ("committed", "done") \
                            and rec[3] is not None:
                        reply = rec[3].encode("latin-1")
                        if traced:
                            sp.stamp(clt_id, req_id, "reply")
                            sp.finish(clt_id, req_id)
                        return (wire.u8(wire.ST_OK)
                                + wire.u64(req_id) + wire.blob(reply))
                    if rec[0] == "aborted" or (rec[0] == "done"
                                               and rec[3] is None):
                        return (wire.u8(ST_TXN_ABORTED)
                                + wire.u64(req_id))
                if not node.is_leader:
                    return _not_leader(daemon, req_id, node=node)
                if time.monotonic() >= deadline:
                    return wire.u8(ST_TIMEOUT) + wire.u64(req_id)
                daemon.commit_cond.wait(0.25)

    return {OP_TXN: clt_txn}
