"""Config, logging, and timing utilities."""

from apus_tpu.utils.config import ClusterSpec, load_config

__all__ = ["ClusterSpec", "load_config"]
