"""Per-process adversarial-time clock (the ``Node.clock`` seam).

Every lease/failure-detector comparison in a live daemon reads time
through ONE per-daemon callable (ReplicaDaemon.clock): the tick stamp,
the fresh-clock lease checks (``Node._fresh_now``), the peer server's
heartbeat-delivery stamp, and the transport's reply-echo stamps all
share it.  That single seam is what makes adversarial time INJECTABLE:
the fault plane scripts rate skew and step jumps into this object
(OP_FAULT ``clock_rate``/``clock_jump``/``clock_reset``) and the whole
replica — but only that replica — experiences the skewed clock, exactly
like a machine whose CLOCK_MONOTONIC drifts.

Semantics:

- ``set_rate(r)``: from now on the clock advances at ``r`` x real time
  (re-anchored at the current value, so the switch is continuous).
  ``r < 1`` is the classically dangerous direction for lease HOLDERS
  (their ``now < lease_until`` keeps passing after real expiry);
  ``r = 0`` freezes the clock outright.
- ``jump(s)``: one-time step of ``s`` seconds.  Forward jumps make
  leases expire EARLY (the safe direction).  Backward jumps cannot make
  the returned value regress — the clock is clamped monotone, so a
  negative jump behaves as a freeze until real time catches up (real
  monotonic clocks never run backwards; a stuck clock is the realistic
  rendering of "time went back").
- ``reset()``: rate back to 1.0 (accumulated offset is kept — offsets
  are indistinguishable from a different boot epoch and removing one
  would need a backward step).

SIGSTOP pauses need no support here: CLOCK_MONOTONIC keeps running
while a process is stopped, so on SIGCONT the resumed replica's clock
has already moved past its leases — which is precisely the property
lease safety rests on, and what the pause nemesis attacks.

Thread-safe; the fast path is one lock + a few floats.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class SkewClock:
    """Monotone per-process clock with scriptable rate skew + jumps."""

    def __init__(self, base: Callable[[], float] = time.monotonic):
        self._base = base
        self._lock = threading.Lock()
        self._rate = 1.0
        self._anchor_real = base()
        self._anchor_val = self._anchor_real
        self._last = self._anchor_val
        #: Skew-event hook (no args): fired after every scripted
        #: set_rate/jump, OUTSIDE the clock lock.  The native data
        #: plane installs its read-gate invalidator here — a gate
        #: deadline projected onto raw CLOCK_MONOTONIC is only valid
        #: while this clock's mapping to real time stands still.
        self.on_skew: "Callable[[], None] | None" = None

    def __call__(self) -> float:
        with self._lock:
            v = self._anchor_val \
                + (self._base() - self._anchor_real) * self._rate
            if v < self._last:
                v = self._last          # monotone clamp (never regress)
            self._last = v
            return v

    def set_rate(self, rate: float) -> None:
        """Advance at ``rate`` x real time from the CURRENT value on
        (continuous: the anchor moves to now, so no step happens)."""
        with self._lock:
            real = self._base()
            self._anchor_val += (real - self._anchor_real) * self._rate
            self._anchor_real = real
            self._rate = max(0.0, float(rate))
        cb = self.on_skew
        if cb is not None:
            cb()

    def jump(self, seconds: float) -> None:
        """One-time step.  Negative steps are absorbed by the monotone
        clamp (the clock freezes until real time catches up)."""
        with self._lock:
            self._anchor_val += float(seconds)
        cb = self.on_skew
        if cb is not None:
            cb()

    def reset(self) -> None:
        """Back to real rate (offset kept; see module docstring)."""
        self.set_rate(1.0)

    @property
    def rate(self) -> float:
        with self._lock:
            return self._rate

    @property
    def skewed(self) -> bool:
        with self._lock:
            return self._rate != 1.0 \
                or self._anchor_val != self._anchor_real
