"""Configuration: file + environment, replacing libconfig + env vars.

The reference splits configuration between environment variables
(per-process identity: server_idx, group_size, server_type, config_path,
dare_log_file, mgid — proxy.c:33-59) and a libconfig file for shared
timing + proxy endpoint (target/nodes.local.cfg, readers
config-dare.c:12-54 / config-proxy.c:6-56).  We keep the same split with
JSON as the file format (stdlib-only).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from apus_tpu.core.types import DEFAULT_LOG_SLOTS, DEFAULT_SLOT_BYTES


@dataclasses.dataclass
class ClusterSpec:
    """Shared cluster configuration (nodes.local.cfg analog)."""

    group_size: int = 3
    # Multi-group sharded consensus (Multi-Raft): the keyspace is
    # sharded into ``groups`` independent consensus groups multiplexed
    # over the SAME daemon set, sockets, fault plane, and device plane
    # (runtime/groupset.py).  Group 0 is the primary (membership
    # service, persistence, bridge); groups 1..N-1 ride OP_GROUP-
    # wrapped frames and coalesced per-peer heartbeats (OP_HB_MULTI).
    # groups == 1 (default) is ZERO-COST: no group machinery is built
    # and every wire frame is byte-identical to the single-group
    # protocol.
    groups: int = 1
    # timing (seconds; reference DEBUG values: hb=10ms, elect=100-300ms,
    # nodes.local.cfg:22-37)
    hb_period: float = 0.010
    hb_timeout: float = 0.050
    elect_low: float = 0.100
    elect_high: float = 0.300
    prune_period: float = 0.500
    # log geometry
    n_slots: int = DEFAULT_LOG_SLOTS
    slot_bytes: int = DEFAULT_SLOT_BYTES
    max_batch: int = 64
    # failure detector: auto-remove dead members via CONFIG entries
    # (check_failure_count analog, dare_server.c:1189-1227); failures
    # counted at most once per fail_window seconds.  The default is
    # sized to the reference's effective eviction delay: its 2-strike
    # rule counts CTRL-QP work-completion errors, which only surface
    # after RDMA retry exhaustion (seconds), so eviction means
    # "continuously dead for ~1s+", never "mid crash-restart cycle" —
    # an eviction during a quick restart forces the returnee through
    # the join protocol, and until that join commits the group runs a
    # member short (one more failure from a stall).
    auto_remove: bool = True
    fail_window: float = 0.500
    # control plane endpoints, one per server idx ("host:port")
    peers: list[str] = dataclasses.field(default_factory=list)
    # proxied application endpoint (config-proxy.c:14-45)
    app_host: str = "127.0.0.1"
    app_port: int = 8888
    # multi-controller device plane (runtime.mesh_plane): one process
    # per replica glued into a global jax.distributed mesh.  Enabled
    # when mesh_coordinator AND mesh_n are set; replicas 0..mesh_n-1
    # each own one device.  mesh_depth = rounds per fixed window;
    # mesh_slots 0 = derive the deployable default from the window
    # shape; mesh_platform "cpu" pins the CPU backend (gloo) for
    # CPU deployments/tests ('' = leave alone on real TPU pods).
    mesh_coordinator: str = ""
    mesh_n: int = 0
    mesh_depth: int = 4
    mesh_slots: int = 0
    mesh_slot_bytes: int = 2048
    mesh_platform: str = "cpu"
    # Mesh-plane RE-FORMATION (runtime.mesh_plane re-formation section):
    # the leader rebuilds the device clique under a new plane epoch when
    # membership re-stabilizes after a death/rejoin (the RC re-handshake
    # analog, dare_ibv_ud.c:1098-1416).  mesh_reform_stable = how long
    # the target clique must be stable (and the plane unhealthy) before
    # the leader acts; mesh_build_timeout = per-epoch rendezvous+compile
    # budget before the attempt is abandoned (epoch burned, retried).
    mesh_reform: bool = True
    mesh_reform_stable: float = 2.0
    mesh_build_timeout: float = 120.0
    # Bounded vote-veto (election-pending quiesce): while an election
    # wants to proceed, an unresolved dispatched window may veto the
    # vote for at most this long before the plane is POISONED (declared
    # dead, degrading to TCP) and the vote proceeds — the immediate-
    # revocation analog of QP reset (dare_ibv_rc.c:2156-2189).  Cheap
    # now that re-formation restores a poisoned plane.  Sizing (see
    # quiesce_ready's safety analysis): early poisoning is
    # unconditionally safe while OUR rank hasn't fed the window's final
    # reduce (the quorum cannot complete without it); the budget only
    # needs to dominate the post-contribution EPILOGUE sliver
    # (receive+finalize, microseconds of work) with a generous
    # oversubscription margin — NOT whole-window execution.
    mesh_election_budget: float = 0.35
    # durability
    db_path: str = "apus_records.db"
    req_log: bool = False
    # Compacting store (runtime.persist compaction): once more than
    # compact_retain records accumulate past the store's last base
    # image, the daemon folds the applied prefix into a fresh base
    # (snapshot record + retained tail), so restart replay — and the
    # delta-snapshot window — is bounded by the RETENTION WINDOW, not
    # history length.  0 disables (append-only store, unbounded
    # replay).  The watchdog polls the gauge every
    # compact_check_period seconds.
    compact_retain: int = 20000
    compact_check_period: float = 5.0
    # fsync policy of the durable record store (runtime.persist):
    # "none" = OS writeback only; "batch" = one fdatasync per
    # group-commit drain window (daemon tick); "always" = per record.
    # Acked-write durability is via REPLICATION under every policy —
    # fsync only narrows full-cluster-power-loss exposure.
    sync_policy: str = "batch"
    # Live-stack fault plane (apus_tpu.parallel.faults): wrap every
    # daemon's transport with seeded, schedule-driven fault injection
    # (drop/delay/duplicate/reorder, asymmetric partitions, throttles,
    # crash hooks).  Off by default — a production daemon pays zero
    # overhead.  fault_schedule is inline JSON or "@/path/to.json";
    # APUS_FAULT_* env vars override/extend (see faults module
    # docstring for the full knob list).
    fault_plane: bool = False
    fault_seed: int = 0
    fault_schedule: str = ""
    # Leader read lease (core.node NodeConfig.read_lease): linearizable
    # reads answered from the leader's local applied state while a
    # quorum-acked heartbeat lease holds — no per-read majority round.
    # Lease duration = hb_timeout * (1 - lease_margin), anchored at the
    # heartbeat round's start; the margin absorbs monotonic clock-rate
    # drift + scheduling skew across replicas.  Disable to force every
    # read through the read-index verification path.
    read_lease: bool = True
    lease_margin: float = 0.2
    # Follower read leases (core.node NodeConfig.follower_read_leases):
    # LINEARIZABLE reads served from every replica's local applied
    # state under commit-index-bounded leases the leader grants in
    # reply to follower requests, nested inside its own leader lease —
    # writes invalidate (commit waits for live lease holders' acks),
    # so a stale local read is structurally impossible within the
    # documented clock assumption (rate drift under lease_margin).
    # Lease-keeping is lazy (requested only while follower-routed GETs
    # are flowing), so leader-only workloads pay nothing.  Distinct
    # from ``follower_reads`` below, which gates STALE app-level reads
    # at the proxy.
    follower_read_leases: bool = True
    # Bucket-granular follower leases (core.node
    # NodeConfig.flr_bucket_leases — Hermes proper, per-KEY write
    # invalidation quantized to the elastic plane's 840 hash buckets):
    # a follower's lease request carries the bucket set its reads
    # touch, commit only waits for a holder's ack on writes whose
    # buckets intersect a live granted set, and a bucket-b follower
    # read waits on b's own log tail instead of the whole log end —
    # one slow holder stops stalling every write in the group, and a
    # hot-key write stream stops gating cold-key follower reads.
    # False = whole-log gating (the measured baseline);
    # APUS_FLR_BUCKETS=0/1 overrides either way.
    flr_bucket_leases: bool = True
    # Native serving data plane (native/dataplane.cpp via
    # apus_tpu/parallel/native_plane.py): client connections are handed
    # to a GIL-released C++ epoll loop that does frame ingest, OP_GROUP
    # demux, endpoint-DB dedup fast-path answers, lease-GET serving
    # from a native applied view, and vectored reply flush — crossing
    # into Python only at the node-lock admission boundary (the
    # group-commit batch hook).  Off by default; APUS_NATIVE_PLANE=1/0
    # overrides the spec either way, and a missing extension falls back
    # LOUDLY to the pure-Python plane (byte-identical wire behavior,
    # pinned by tests/test_native_plane.py).
    native_plane: bool = False
    # Misdirection gate: False (default) = a non-leader's proxy REFUSES
    # client bytes to its raw app (the client reconnects and finds the
    # leader — structurally no unreplicated reads/writes; beyond the
    # reference, whose clients must FindLeader themselves).  True =
    # allow stale follower reads (verification harnesses, maintenance).
    # Runtime-flippable per daemon via the OP_MAINT_READS wire op.
    follower_reads: bool = False

    @staticmethod
    def from_dict(d: dict) -> "ClusterSpec":
        known = {f.name for f in dataclasses.fields(ClusterSpec)}
        return ClusterSpec(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class ProcessEnv:
    """Per-process identity from environment (proxy.c:33-59 analog)."""

    server_idx: int = 0
    group_size: int = 3
    server_type: str = "start"          # start | join | loggp
    config_path: Optional[str] = None
    log_file: Optional[str] = None

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "ProcessEnv":
        e = os.environ if env is None else env
        return ProcessEnv(
            server_idx=int(e.get("APUS_SERVER_IDX", e.get("server_idx", 0))),
            group_size=int(e.get("APUS_GROUP_SIZE", e.get("group_size", 3))),
            server_type=e.get("APUS_SERVER_TYPE", e.get("server_type", "start")),
            config_path=e.get("APUS_CONFIG", e.get("config_path")),
            log_file=e.get("APUS_LOG_FILE", e.get("dare_log_file")),
        )


def load_config(path: Optional[str] = None) -> ClusterSpec:
    if path is None or not os.path.exists(path):
        return ClusterSpec()
    with open(path) as f:
        return ClusterSpec.from_dict(json.load(f))
