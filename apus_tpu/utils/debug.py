"""Leveled per-server logging (debug.h analog: info/debug/error macros to
per-server FILE*, reference include/dare/debug.h:24-92)."""

from __future__ import annotations

import logging
import os
import sys


def make_logger(name: str, log_file: str | None = None,
                level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if logger.handlers:
        return logger
    logger.setLevel(level)
    handler = (logging.FileHandler(log_file) if log_file
               else logging.StreamHandler(sys.stderr))
    handler.setFormatter(logging.Formatter(
        "[%(asctime)s.%(msecs)03d] %(name)s: %(message)s", "%H:%M:%S"))
    logger.addHandler(handler)
    logger.propagate = False
    return logger


def server_logger(idx: int, log_dir: str | None = None) -> logging.Logger:
    """Per-server log file srv<i>.log (run.sh greps these to find the
    leader, benchmarks/run.sh:46-68 — our ops tooling does the same)."""
    path = os.path.join(log_dir, f"srv{idx}.log") if log_dir else None
    return make_logger(f"apus.srv{idx}", path)
