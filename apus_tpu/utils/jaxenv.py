"""Backend-selection helper for CLIs and tests.

The image's ``sitecustomize`` registers the axon TPU PJRT plugin at
interpreter start and forces ``jax_platforms="axon,cpu"`` — so the
``JAX_PLATFORMS=cpu`` environment variable alone does NOT keep a
process off the (single, intermittently wedged) tunneled TPU chip.
Every entry point that honors a CPU request must also set the config
knob before any backend initializes.  One helper so the dance lives in
one place for the CLIs (bench.py, meshcheck, loggp); tests/conftest.py
keeps its own UNCONDITIONAL variant — it also forces the env vars
before any import, which this opt-in helper deliberately does not."""

from __future__ import annotations

import os


def respect_cpu_request() -> bool:
    """If the caller asked for CPU via ``JAX_PLATFORMS=cpu``, force the
    jax config knob to match (must run before backend init).  Returns
    True when CPU was requested."""
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        return False
    import jax
    jax.config.update("jax_platforms", "cpu")
    return True
