"""ctypes binding for the native durable record store (libapusstore).

The reference binds BerkeleyDB from C (src/db/db-interface.c); our
native store is C++ (native/store.cpp) and this module is the Python
daemon's handle to it.  A pure-Python fallback with identical semantics
exists for environments without a toolchain (and to cross-check the
native implementation in tests).
"""

from __future__ import annotations

import ctypes
import errno
import logging
import os
import struct
import subprocess
import threading
import zlib
from typing import Optional

_log = logging.getLogger("apus.store")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.environ.get(
    "APUS_NATIVE_DIR", os.path.join(_REPO_ROOT, "native"))

_lib = None
_lib_lock = threading.Lock()


def _load_lib() -> Optional[ctypes.CDLL]:
    """Load libapusstore.so, building it on first use."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so = os.path.join(_NATIVE_DIR, "build", "libapusstore.so")
        if not os.path.exists(so):
            src = os.path.join(_NATIVE_DIR, "store.cpp")
            if not os.path.exists(src):
                return None
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "store"],
                               check=True, capture_output=True,
                               timeout=120)
            except (subprocess.SubprocessError, OSError):
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        lib.apus_store_open.restype = ctypes.c_void_p
        lib.apus_store_open.argtypes = [ctypes.c_char_p]
        lib.apus_store_append.restype = ctypes.c_uint64
        lib.apus_store_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_uint32]
        lib.apus_store_sync.restype = ctypes.c_int
        lib.apus_store_sync.argtypes = [ctypes.c_void_p]
        lib.apus_store_count.restype = ctypes.c_uint64
        lib.apus_store_count.argtypes = [ctypes.c_void_p]
        lib.apus_store_payload_bytes.restype = ctypes.c_uint64
        lib.apus_store_payload_bytes.argtypes = [ctypes.c_void_p]
        lib.apus_store_dump_size.restype = ctypes.c_uint64
        lib.apus_store_dump_size.argtypes = [ctypes.c_void_p]
        lib.apus_store_dump.restype = ctypes.c_uint64
        lib.apus_store_dump.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64]
        lib.apus_store_load_dump.restype = ctypes.c_uint64
        lib.apus_store_load_dump.argtypes = [ctypes.c_void_p,
                                             ctypes.c_char_p,
                                             ctypes.c_uint64]
        lib.apus_store_close.restype = None
        lib.apus_store_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeRecordStore:
    """Handle to a libapusstore file (store_record/dump_records parity,
    db-interface.c:65-128)."""

    def __init__(self, path: str):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("libapusstore unavailable")
        self._lib = lib
        self._h = lib.apus_store_open(path.encode())
        if not self._h:
            raise OSError(f"apus_store_open({path!r}) failed")
        self.path = path

    def append(self, data: bytes) -> int:
        n = self._lib.apus_store_append(self._h, data, len(data))
        if n == 0:
            raise OSError("apus_store_append failed")
        return n

    def sync(self) -> None:
        if self._lib.apus_store_sync(self._h) != 0:
            raise OSError("apus_store_sync failed")

    @property
    def count(self) -> int:
        return self._lib.apus_store_count(self._h)

    @property
    def payload_bytes(self) -> int:
        return self._lib.apus_store_payload_bytes(self._h)

    def dump(self) -> bytes:
        size = self._lib.apus_store_dump_size(self._h)
        buf = ctypes.create_string_buffer(size)
        w = self._lib.apus_store_dump(self._h, buf, size)
        if w != size:
            raise OSError("apus_store_dump failed")
        return buf.raw[:w]

    def load_dump(self, blob: bytes) -> int:
        n = self._lib.apus_store_load_dump(self._h, blob, len(blob))
        if n == (1 << 64) - 1:
            raise OSError("apus_store_load_dump failed")
        return n

    def records(self) -> list[bytes]:
        return parse_dump(self.dump())

    def close(self) -> None:
        if self._h:
            self._lib.apus_store_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PyRecordStore:
    """Pure-Python reference implementation (bit-identical file format)."""

    _MAGIC = b"APUSTOR1"

    def __init__(self, path: str):
        self.path = path
        self.count = 0
        self.payload_bytes = 0
        #: quarantine destination when the header was corrupt (None =
        #: clean open); the daemon surfaces this loudly
        self.quarantined: Optional[str] = None
        self._offsets: list[tuple[int, int]] = []   # (offset, len)
        create = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "r+b" if not create else "w+b")
        if create:
            self._f.write(self._MAGIC)
            self._f.flush()
            self._size = len(self._MAGIC)
        else:
            self._scan()

    def _quarantine(self) -> None:
        """Corrupt 8-byte header: the file is unreadable as a store.
        Raising here crash-looped the daemon forever on restart (every
        re-exec re-hit the same bytes); instead QUARANTINE — rename the
        file aside, log loudly, start empty.  The replica then rejoins
        via normal catch-up (entry re-replication or a leader snapshot
        push), during which the store is rebuilt as a valid prefix."""
        self._f.close()
        dst = quarantine_path(self.path)
        os.replace(self.path, dst)
        self.quarantined = dst
        _log.error("store %s has a corrupt header; quarantined to %s "
                   "and starting empty (replica rejoins via catch-up)",
                   self.path, dst)
        self._f = open(self.path, "w+b")
        self._f.write(self._MAGIC)
        self._f.flush()
        self._size = len(self._MAGIC)
        self._offsets = []
        self.count = 0
        self.payload_bytes = 0

    def _scan(self) -> None:
        f = self._f
        f.seek(0, os.SEEK_END)
        total = f.tell()
        f.seek(0)
        if f.read(8) != self._MAGIC:
            self._quarantine()
            return
        off = 8
        while off + 8 <= total:
            f.seek(off)
            hdr = f.read(8)
            if len(hdr) < 8:
                break
            ln, crc = struct.unpack("<II", hdr)
            if off + 8 + ln > total:
                break
            data = f.read(ln)
            if len(data) < ln or (zlib.crc32(data) & 0xFFFFFFFF) != crc:
                break
            self._offsets.append((off + 8, ln))
            self.count += 1
            self.payload_bytes += ln
            off += 8 + ln
        self._size = off
        if off < total:
            f.truncate(off)          # torn tail

    def append(self, data: bytes) -> int:
        f = self._f
        f.seek(self._size)
        f.write(struct.pack("<II", len(data),
                            zlib.crc32(data) & 0xFFFFFFFF))
        f.write(data)
        f.flush()
        self._offsets.append((self._size + 8, len(data)))
        self._size += 8 + len(data)
        self.count += 1
        self.payload_bytes += len(data)
        return self.count

    def sync(self) -> None:
        self._f.flush()
        os.fdatasync(self._f.fileno())

    def dump(self) -> bytes:
        out = [struct.pack("<Q", self.count)]
        for off, ln in self._offsets:
            self._f.seek(off)
            out.append(struct.pack("<I", ln))
            out.append(self._f.read(ln))
        return b"".join(out)

    def load_dump(self, blob: bytes) -> int:
        records = parse_dump(blob)
        self._f.truncate(0)
        self._f.seek(0)
        self._f.write(self._MAGIC)
        self._size = len(self._MAGIC)
        self._offsets = []
        self.count = 0
        self.payload_bytes = 0
        for r in records:
            self.append(r)
        return self.count

    def records(self) -> list[bytes]:
        return parse_dump(self.dump())

    def close(self) -> None:
        if self._f is not None:
            self.sync()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def quarantine_path(path: str) -> str:
    """First free ``<path>.corrupt[.N]`` name (quarantined stores are
    kept for post-mortem, never reused)."""
    dst = path + ".corrupt"
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{path}.corrupt.{n}"
    return dst


class FaultStore:
    """Deterministic disk-fault injection around a record store.

    The live restart path (``Persistence``/``replay_into``/daemon
    restart) had zero fault coverage — torn tails, latent CRC
    corruption, fsync EIO and disk-full were all untested on the real
    recovery code.  This wrapper schedules each fault class at an
    APPEND/SYNC ORDINAL (1-based, deterministic — campaigns derive the
    ordinals from their seed):

    - ``torn_at=N``: after append N succeeds in memory, the record's
      tail is TRUNCATED on disk (a crash mid-write: the page cache
      made it to the platter only partially).  The daemon keeps
      running none the wiser; the next open truncates back to record
      N-1 and the replica re-fetches via catch-up.
    - ``crc_at=N``: one payload byte of record N is flipped on disk
      (latent media corruption).  Recovery treats it exactly like a
      torn tail: scan stops there, later records are dropped.
    - ``fsync_eio_at=N``: the Nth and every later ``sync()`` raises
      EIO (dying disk).  The daemon's persistence wrapper must disable
      persistence and keep serving.
    - ``enospc_at=N``: the Nth and every later ``append()`` raises
      ENOSPC (disk full) BEFORE touching the file.

    Configured directly in tests, or per-daemon-process via
    ``APUS_DISKFAULT_TORN/CRC/FSYNC_EIO/ENOSPC`` env vars (applied by
    ``open_store``; ProcCluster passes per-replica env).
    """

    def __init__(self, inner, torn_at: int = 0, crc_at: int = 0,
                 fsync_eio_at: int = 0, enospc_at: int = 0):
        self._inner = inner
        self.torn_at = torn_at
        self.crc_at = crc_at
        self.fsync_eio_at = fsync_eio_at
        self.enospc_at = enospc_at
        self._syncs = 0

    def append(self, data: bytes) -> int:
        if self.enospc_at and self._inner.count + 1 >= self.enospc_at:
            raise OSError(errno.ENOSPC,
                          "No space left on device (injected)")
        n = self._inner.append(data)
        if self.torn_at and n == self.torn_at:
            self._corrupt(data, torn=True)
        elif self.crc_at and n == self.crc_at:
            self._corrupt(data, torn=False)
        return n

    def _corrupt(self, data: bytes, torn: bool) -> None:
        """Damage the just-appended record ON DISK ONLY — the running
        store's in-memory view stays valid, so later appends continue
        past the damage (scan stops at the first bad record, exactly
        the mid-file-corruption recovery branch)."""
        try:
            self._inner.sync()          # ensure the bytes are visible
        except OSError:
            pass
        rec_len = 8 + len(data)
        with open(self._inner.path, "r+b") as f:
            f.seek(0, os.SEEK_END)
            end = f.tell()
            if torn:
                # Cut into the payload (or the header for empty
                # records): a partial write at crash.
                cut = max(1, len(data) // 2 + 1) if data else 5
                f.truncate(end - min(cut, rec_len - 1))
            else:
                off = end - 1 - len(data) // 2 if data else end - 5
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0xFF]))
        _log.warning("FaultStore: injected %s at record %d of %s",
                     "torn tail" if torn else "CRC flip",
                     self._inner.count, self._inner.path)

    def sync(self) -> None:
        self._syncs += 1
        if self.fsync_eio_at and self._syncs >= self.fsync_eio_at:
            raise OSError(errno.EIO, "fsync failed (injected)")
        self._inner.sync()

    def __getattr__(self, name: str):
        # count/payload_bytes/path/dump/load_dump/records/close ...
        return getattr(self._inner, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def diskfaults_from_env(env: Optional[dict] = None) -> Optional[dict]:
    """Collect APUS_DISKFAULT_* knobs; None when unset/zero."""
    e = os.environ if env is None else env
    cfg = {}
    for var, key in [("APUS_DISKFAULT_TORN", "torn_at"),
                     ("APUS_DISKFAULT_CRC", "crc_at"),
                     ("APUS_DISKFAULT_FSYNC_EIO", "fsync_eio_at"),
                     ("APUS_DISKFAULT_ENOSPC", "enospc_at")]:
        try:
            v = int(e.get(var, "") or 0)
        except ValueError:
            v = 0
        if v > 0:
            cfg[key] = v
    return cfg or None


def parse_dump(blob: bytes) -> list[bytes]:
    """Decode the dump format: u64 count | (u32 len | data)*."""
    (count,) = struct.unpack_from("<Q", blob, 0)
    out = []
    off = 8
    for _ in range(count):
        (ln,) = struct.unpack_from("<I", blob, off)
        off += 4
        out.append(blob[off:off + ln])
        off += ln
    return out


def open_store(path: str, prefer_native: bool = True):
    """Open the durable store, preferring the native implementation.
    A corrupt header makes the native open fail (store.cpp returns
    NULL), so the Python fallback — whose ``_scan`` quarantines — is
    also the corrupt-header recovery path for native-preferring
    daemons: either way the open SUCCEEDS with an empty store instead
    of crash-looping the daemon.  APUS_DISKFAULT_* env knobs wrap the
    result in a :class:`FaultStore` (chaos campaigns only)."""
    store = None
    if prefer_native:
        try:
            store = NativeRecordStore(path)
        except (RuntimeError, OSError):
            pass
    if store is None:
        store = PyRecordStore(path)
    cfg = diskfaults_from_env()
    if cfg:
        store = FaultStore(store, **cfg)
    return store
