"""High-resolution timing (timer.h analog).

The reference uses x86 ``rdtsc`` with frequency calibration
(include/dare/timer.h:23-61); on our hosts ``time.perf_counter_ns`` is the
portable monotonic clock.  Scoped timers mirror TIMER_INIT/START/STOP/INFO
(timer.h:75-91) and feed the stats/observability layer.
"""

from __future__ import annotations

import math
import time


def now() -> float:
    return time.perf_counter()


def now_ns() -> int:
    return time.perf_counter_ns()


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) of an ASCENDING list.

    The one shared convention for benchmark percentile rows (the
    harnesses used to hand-roll three slightly different ranks)."""
    if not sorted_vals:
        return float("nan")
    k = min(len(sorted_vals) - 1,
            max(0, math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return sorted_vals[k]


class ScopedTimer:
    def __init__(self, name: str = ""):
        self.name = name
        self.samples_ns: list[int] = []
        self._t0 = 0

    def __enter__(self):
        self._t0 = now_ns()
        return self

    def __exit__(self, *exc):
        self.samples_ns.append(now_ns() - self._t0)
        return False

    def percentile(self, p: float) -> float:
        """p in [0,100]; returns microseconds."""
        if not self.samples_ns:
            return 0.0
        s = sorted(self.samples_ns)
        k = min(len(s) - 1, int(round(p / 100.0 * (len(s) - 1))))
        return s[k] / 1000.0

    def summary(self) -> dict:
        return {"name": self.name, "n": len(self.samples_ns),
                "p50_us": self.percentile(50), "p99_us": self.percentile(99)}
