"""Consensus-commit benchmark.  Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures the per-round commit latency of the device-resident PIPELINED
commit path: ``depth`` consecutive commit rounds — each a full
leader->replicas scatter of a 64-entry batch, fence check, quorum
reduction, commit advance — execute inside one XLA program
(ops.commit.build_pipelined_commit_step), so the host dispatch cost is
amortized across rounds.  This mirrors how the reference reaches its
own numbers: its RDMA commit loop keeps many unsignaled WRs outstanding
and overlaps rounds in the NIC queue (post_send selective signaling,
dare_ibv_rc.c:2552-2568); ours keeps the round loop in HBM/MXU-land.

Baseline: the reference repository publishes no numbers (BASELINE.md).
We baseline against the DARE/APUS RDMA envelope of ~15 us per commit
round on FDR InfiniBand (the order of magnitude the papers and the
repo's production timing constants imply: hb=1 ms, elect=10-30 ms,
nodes.local.cfg) — for a 64-entry batched round, per-entry cost
15/64 ~= 0.23 us.  vs_baseline = baseline_p50 / our_p50 (>1 is better
than baseline).

Robustness: this file is its own watchdog.  The parent process probes
tunnel health cheaply (a 15 s trivial-jit child) and only spends a full
attempt window (a watched child of this same file,
``_APUS_BENCH_CHILD=1``) on a healthy probe, re-probing until the
budget forces the forced-CPU fallback (the axon tunnel wedges for
minutes at a time and clears on its own).  The child climbs a DEPTH
LADDER (default 4096 -> ... -> 1048576 rounds per dispatch on TPU),
flushing a complete JSON headline after every depth — a watchdog kill
mid-ladder still leaves the best completed number on stdout, and the
parent takes the LAST JSON line.  A successful TPU result is recorded
(with a content fingerprint of the measured sources) in
BENCH_TPU_LAST.json; a CPU fallback attaches it as timestamped
supplementary evidence only while the fingerprint still matches.  Per-phase progress
goes to stderr so a timeout is diagnosable (backend init vs compile vs
execute).  The JAX persistent compilation cache turns repeat compiles
into disk hits.

Env knobs: APUS_BENCH_DEPTHS (comma ladder, default
"4096,16384,65536,262144,1048576" TPU / "64,1024,16384" CPU),
APUS_BENCH_BUDGET (total seconds, default 225),
APUS_BENCH_TPU_TIMEOUT (per-TPU-attempt watchdog, default 60),
APUS_JAX_CACHE (compilation cache dir, default <repo>/.jax_cache).

--throughput: the REPLICATED commits/sec mode (no JAX): 16 serial vs
16 pipelined clients against a live 3-replica LocalCluster — raw
loopback and under an emulated client-link RTT — plus a max_batch=1
control isolating group-commit and lease vs read-index GET rows.  See
_bench_throughput.

--single-window: the UN-AMORTIZED latency mode.  Instead of the depth
ladder it dispatches the windowed commit engine
(ops.commit.build_windowed_commit_step — ONE compiled program, runtime
round count, early exit on the quorum vote) for depth-1 and depth-4
windows and reports, per depth, the WALL p50 a client-facing request
would see AND a profiler-derived DEVICE-time figure (jax.profiler
trace parsing): wall is RTT-dominated on a tunneled chip (the r05
single_dispatch_round_p50_us of 69 ms was pure dispatch RTT), so
device time is the number the north star's "p50 commit latency"
actually names.  Same watchdog/fallback scaffolding as the default
mode.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_ROUND_US = 15.0        # RDMA commit-round envelope (see docstring)
#: BENCH_r05.json single_dispatch_round_p50_us — the 69 ms wall one
#: un-amortized dispatch paid on the tunneled TPU; the --single-window
#: mode's baseline (ISSUE 1).
R05_SINGLE_DISPATCH_US = 69374.63
_T0 = time.monotonic()


def _mark(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _bench() -> None:
    """Child process: run the measurement on whatever backend JAX gives
    us and print a JSON line per completed ladder depth.  May hang or
    die — the parent watches and keeps the last flushed line."""
    _mark("importing jax")
    import jax

    from apus_tpu.utils.jaxenv import respect_cpu_request
    respect_cpu_request()         # env alone can't evade sitecustomize

    cache = os.environ.get(
        "APUS_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    if cache:
        # Backend-keyed cache dir: XLA:CPU AOT entries record the
        # compile machine's feature set and a TPU-attempt process and a
        # forced-CPU process sharing one dir can hand each other
        # results the host rejects (or worse, SIGILLs on).
        jax.config.update("jax_compilation_cache_dir",
                          f"{cache}-{jax.default_backend()}")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from apus_tpu.core.cid import Cid
    from apus_tpu.ops.commit import (CommitControl, build_commit_step,
                                     build_pipelined_commit_step_fused,
                                     place_batch)
    from apus_tpu.ops.logplane import host_batch_to_device, make_device_log
    from apus_tpu.ops.mesh import replica_mesh, replica_sharding

    _mark("initializing backend")
    backend = jax.default_backend()
    devices = jax.devices()
    _mark(f"backend={backend} devices={devices}")
    cpu = backend == "cpu"
    R, S, SB, B = 5, 4096, 4096, 64      # 5 replicas, 16 MB log each, 64-batch
    depths = [int(d) for d in os.environ.get(
        "APUS_BENCH_DEPTHS",
        "64,1024,16384" if cpu
        else "4096,16384,65536,262144,1048576").split(",")]
    dispatches = 5 if cpu else 10
    single_iters = 10 if cpu else 20
    deadline = float(os.environ.get("_APUS_BENCH_DEADLINE", "0"))
    mesh = replica_mesh(R, devices=devices[:1])
    sh = replica_sharding(mesh)
    cid = Cid.initial(R)

    # Redis-SET-shaped payloads (the run.sh benchmark shape: redis-benchmark
    # -t set, benchmarks/run.sh:70-80).  SD distinct staged batches ride
    # the pipeline (round i consumes batch i % SD): the steady state
    # commits varied payloads, not one batch re-committed.
    SD = 16
    sd_np = np.zeros((SD, R, B, SB), np.uint8)
    sm_np = np.zeros((SD, R, B, 4), np.int32)
    reqs = bd = bm = None
    for k in range(SD):
        batch_reqs = [
            b"*3\r\n$3\r\nSET\r\n$16\r\nkey:%012d\r\n$64\r\n%s\r\n"
            % (k * B + i, bytes([97 + (k + i) % 26]) * 64)
            for i in range(B)]
        kd, km, _ = host_batch_to_device(batch_reqs, SB, batch_size=B)
        sd_np[k, 0], sm_np[k, 0] = kd, km        # leader row 0 only
        if k == 0:
            reqs, bd, bm = batch_reqs, kd, km    # reused by later phases
    bdata, bmeta = place_batch(mesh, R, 0, bd, bm)
    from jax.sharding import NamedSharding, PartitionSpec as _P
    from apus_tpu.ops.mesh import REPLICA_AXIS as _AX
    ssh = NamedSharding(mesh, _P(None, _AX))
    sdata = jax.device_put(sd_np, ssh)
    smeta = jax.device_put(sm_np, ssh)
    _mark(f"{SD} staged batches placed on device")

    best = None            # (round_p50, depth, wall_p50, walls)
    per_depth = {}
    ladder_conf = {}       # pallas_mode + geometry of the headline ladder

    def emit(single_p50=None, **extra_detail):
        round_p50, D, wall_p50, _ = best
        per_entry_p50 = round_p50 / B
        commits_per_sec = 1e6 / round_p50      # rounds (quorum commits)/sec
        result = {
            "metric": "commit_round_p50_latency_batch64_5rep_pipelined",
            "value": round(round_p50, 3),
            "unit": "us",
            "vs_baseline": round(BASELINE_ROUND_US / round_p50, 4),
            "detail": {
                "backend": backend,
                **ladder_conf,
                "pipeline_depth": D,
                "depth_ladder_round_p50_us": {
                    str(d): round(v, 3) for d, v in per_depth.items()},
                "dispatch_wall_p50_us": round(wall_p50, 1),
                "single_dispatch_round_p50_us":
                    None if single_p50 is None else round(single_p50, 2),
                "per_entry_p50_us": round(per_entry_p50, 4),
                "commits_per_sec": round(commits_per_sec),
                "entries_per_sec": round(commits_per_sec * B),
                "batch": B, "replicas": R, "slot_bytes": SB,
                "baseline_round_us": BASELINE_ROUND_US,
                **extra_detail,
            },
        }
        print(json.dumps(result), flush=True)

    # -- pipelined steady state (headline), climbing the depth ladder -----
    # The fused (closed-form) pipelined step: the whole depth-D window is
    # one bulk ring update + vectorized quorum math (ops.commit, same
    # strength reduction as the reference's entry-range RDMA WRITEs).
    # Each timed iteration reads the final commit index back to the host
    # — the leader host needs it to release spinning app threads
    # (proxy.c:160 analog), so the readback is part of the round, and it
    # is also what makes the timing honest on the async axon tunnel
    # (block_until_ready alone under-measures there).
    for D in depths:
        if deadline and time.time() > deadline - 15:
            _mark(f"deadline near; stopping ladder before depth {D}")
            break
        t_c = time.monotonic()
        pipe = build_pipelined_commit_step_fused(mesh, R, S, SB, B, depth=D,
                                                 staged_depth=SD)
        # Attribution: WHICH data path produced the number — the
        # compiled pallas in-place ring kernel or the XLA whole-ring
        # select ('off') — plus the ladder geometry.
        ladder_conf.update(pallas_mode=pipe.pallas_mode,
                           ladder_n_slots=S, ladder_staged_batches=SD)
        devlog = make_device_log(R, S, SB, batch=B, leader=0, term=1,
                                 sharding=sh)
        ctrl = CommitControl.from_cid(cid, R, 0, 1, 1)
        devlog, commits, ctrl = pipe(devlog, sdata, smeta, ctrl)   # compile
        assert int(np.asarray(commits)[-1]) == 1 + D * B, \
            "pipeline did not commit"
        # One more chained warmup: feeding device-resident outputs back
        # re-specializes the program once; measure after that.
        devlog, commits, ctrl = pipe(devlog, sdata, smeta, ctrl)
        int(np.asarray(commits)[-1])
        _mark(f"depth={D}: compiled+warm in {time.monotonic() - t_c:.1f}s")
        walls_us = []
        expect = None
        for _ in range(dispatches):
            t0 = time.perf_counter_ns()
            devlog, commits, ctrl = pipe(devlog, sdata, smeta, ctrl)
            got = int(commits[-1])   # single-scalar readback: all the
            walls_us.append((time.perf_counter_ns() - t0) / 1e3)
            # leader host needs is the final commit index; fetching the
            # whole [D] vector would inflate the timed region with a
            # transfer the production driver never performs.
            assert expect is None or got == expect, (got, expect)
            expect = got + D * B
        walls_us.sort()
        wall_p50 = walls_us[len(walls_us) // 2]
        round_p50 = wall_p50 / D
        per_depth[D] = round_p50
        _mark(f"depth={D}: round p50 {round_p50:.2f}us "
              f"(dispatch {wall_p50:.0f}us)")
        if best is None or round_p50 < best[0]:
            best = (round_p50, D, wall_p50, walls_us)
        # Flush NOW: a watchdog kill later in the ladder must not
        # forfeit this completed measurement (the parent parses the
        # LAST JSON line, so deeper-ladder re-emits supersede).
        emit()

    if best is None:
        return

    # -- single-dispatch round (for reference; RTT-dominated on tunnel) ---
    # Skipped when the watchdog deadline is near: a second slow compile
    # must not push the process into the kill window.
    if deadline and time.time() > deadline - 30:
        return
    _mark("measuring single-dispatch round")
    step = build_commit_step(mesh, R, S, SB, B, auto_advance=True)
    devlog1 = make_device_log(R, S, SB, batch=B, leader=0, term=1,
                              sharding=sh)
    c1 = CommitControl.from_cid(cid, R, 0, 1, 1)
    cur, _, commit, c1 = step(devlog1, bdata, bmeta, c1)
    int(np.asarray(commit))
    lat = []
    for _ in range(single_iters):
        t0 = time.perf_counter_ns()
        cur, _, commit, c1 = step(cur, bdata, bmeta, c1)
        int(np.asarray(commit))
        lat.append((time.perf_counter_ns() - t0) / 1e3)
    lat.sort()
    _mark(f"single-dispatch round p50 {lat[len(lat) // 2]:.0f}us")
    emit(lat[len(lat) // 2])

    # -- LIVE runner round (the un-idealized path): host wire-encode +
    # place_batch staging + dispatch + readback per round, through the
    # production DeviceCommitRunner.commit_round the daemons use.
    # 45 s margin: the runner compiles ITS OWN programs (plain commit
    # step + gather/offs helpers — not cache hits of the steps above),
    # and an overrun here would forfeit the whole attempt.
    if deadline and time.time() > deadline - 45:
        return
    _mark("measuring live runner round (host staging included)")
    from apus_tpu.core.log import LogEntry
    from apus_tpu.core.types import EntryType
    from apus_tpu.runtime.device_plane import DeviceCommitRunner

    # Live ring sized so the deep ladder's 64-round windows pass the
    # driver's ring-capacity gate with MAX_INFLIGHT async windows in
    # flight ((inflight+K)*B <= n_slots) — i.e. the async measurement
    # below is a deployable drain-able configuration, not bench-only.
    S_live = max(S, 16384) if not cpu else S
    runner = DeviceCommitRunner(n_replicas=R, n_slots=S_live, slot_bytes=SB,
                                batch=B, devices=devices[:1])
    gen = runner.reset(leader=0, term=1, first_idx=1)
    live = set(range(R))
    payload = reqs[0]

    def batch_at(end0):
        return [LogEntry(idx=end0 + j, term=1, type=EntryType.CSM,
                         req_id=j + 1, clt_id=1, data=payload)
                for j in range(B)]

    end0 = 1
    runner.commit_round(gen, end0, batch_at(end0), cid, live)   # warm
    end0 += B
    lat2 = []
    for _ in range(single_iters):
        t0 = time.perf_counter_ns()
        res = runner.commit_round(gen, end0, batch_at(end0), cid, live)
        lat2.append((time.perf_counter_ns() - t0) / 1e3)
        assert res is not None and res[1] == end0 + B, res
        end0 += B
    lat2.sort()
    live_p50 = lat2[len(lat2) // 2]
    _mark(f"live runner round p50 {live_p50:.0f}us")
    # Flush NOW: a watchdog kill inside the deep-window phase below
    # must not forfeit this completed measurement.
    emit(lat[len(lat) // 2], live_runner_round_p50_us=round(live_p50, 2))

    # Deep-window live LADDER: the driver's production shapes under
    # backlog — each rung K dispatches K rounds per commit_rounds call
    # (fused closed-form on an accelerator, scan shape on CPU; see
    # DeviceCommitRunner._build) through the same entry the daemons
    # use, host wire-encoding and staging included.  The driver picks
    # the deepest rung the backlog covers (DEEP_DEPTHS), so these ARE
    # the live per-round costs at increasing backlog, not idealized
    # re-commits of resident batches.
    live_ladder = {}
    live_detail = dict(live_runner_round_p50_us=round(live_p50, 2),
                       live_deep_depths=list(runner.window_depths),
                       live_pallas_modes={str(k): v for k, v in
                                          runner.pallas_modes.items()})

    def window_at(e0, rounds):
        return [LogEntry(idx=e0 + j, term=1, type=EntryType.CSM,
                         req_id=j + 1, clt_id=1, data=payload)
                for j in range(rounds * B)]

    for D_live in sorted(k for k in runner.window_depths
                         if k >= runner.DEEP_DEPTH):
        if deadline and time.time() > deadline - 20:
            break
        runner.commit_rounds(gen, end0, window_at(end0, D_live), cid,
                             live)   # warm
        end0 += D_live * B
        lat3 = []
        for _ in range(max(3, single_iters // 4)):
            t0 = time.perf_counter_ns()
            got = runner.commit_rounds(gen, end0, window_at(end0, D_live),
                                       cid, live)
            lat3.append((time.perf_counter_ns() - t0) / 1e3)
            assert got == end0 + D_live * B, (got, end0)
            end0 += D_live * B
        lat3.sort()
        live_ladder[D_live] = lat3[len(lat3) // 2] / D_live
        _mark(f"live window depth={D_live}: round p50 "
              f"{live_ladder[D_live]:.0f}us")
        best_D = min(live_ladder, key=live_ladder.get)
        live_detail.update(
            live_window_ladder_round_p50_us={
                str(d): round(v, 2) for d, v in live_ladder.items()},
            live_window_round_p50_us=round(live_ladder[best_D], 2),
            live_window_depth=best_D)
        # Flush after every rung (parent keeps the LAST JSON line).
        emit(lat[len(lat) // 2], **live_detail)

    if not live_ladder:
        return

    # ASYNC pipelined live path: MAX_INFLIGHT deep windows kept in
    # flight (runner.commit_rounds_async / resolve_rounds — what the
    # driver does under sustained backlog), so window N+1's staging +
    # dispatch overlaps window N's execution+readback.  Mean over a
    # continuous pipeline, since rounds no longer have individual
    # walls.  Depth = the deepest rung whose in-flight footprint fits
    # the live ring (the driver's own capacity gate: (inflight+K)*B <=
    # n_slots), so this is a deployable configuration, not a bench-only
    # shape.
    if deadline and time.time() > deadline - 15:
        return
    from apus_tpu.runtime.device_plane import DevicePlaneDriver
    inflight_cap = DevicePlaneDriver.MAX_INFLIGHT
    D_async = max(
        (k for k in live_ladder
         if (inflight_cap + k) * B <= runner.n_slots),
        default=runner.DEEP_DEPTH)
    iters = max(6, single_iters // 2)
    pending = []
    t0 = time.perf_counter_ns()
    for _ in range(iters):
        h = runner.commit_rounds_async(gen, end0, window_at(end0, D_async),
                                       cid, live)
        assert h is not None
        pending.append(h)
        end0 += D_async * B
        if len(pending) >= inflight_cap:
            got = runner.resolve_rounds(pending.pop(0))
            assert got is not None
    while pending:
        got = runner.resolve_rounds(pending.pop(0))
        assert got is not None
    async_mean = (time.perf_counter_ns() - t0) / 1e3 / (iters * D_async)
    _mark(f"live runner ASYNC {inflight_cap}-deep pipeline round mean "
          f"{async_mean:.0f}us ({iters} windows x {D_async} rounds)")
    emit(lat[len(lat) // 2], **live_detail,
         live_async_round_mean_us=round(async_mean, 2),
         live_async_inflight=inflight_cap,
         live_async_depth=D_async)


def _trace_device_time(trace_dir: str):
    """Parse a ``jax.profiler`` trace directory into TOTAL on-device
    busy time in us (plus the signal it came from).

    The profiler drops gzipped Chrome-trace JSON next to the xplane
    protos, so this needs no tensorboard/tensorflow dependency.  Two
    signals, best first:

    Both signals are per-thread interval UNIONS of complete events —
    nested op events must not be double-counted, and gaps between
    program launches must not be billed as device time:

    - a ``/device:``-named process (TPU/GPU): every thread on that
      track is device execution;
    - the CPU backend has no device track: its compute runs on the
      ``tf_XLATfrtCpuClient`` threadpool threads of the host process,
      so union over those (NOT ``TfrtCpuExecutable::ExecuteHelper`` —
      the thunk executor dispatches asynchronously, and the helper
      span covers only the enqueue on a warm pipeline).

    Returns ``(total_us, n_events, source)`` or ``None`` when no trace
    was written / neither signal exists (e.g. a tunnel that doesn't
    forward device profiling) — callers report the miss, never a 0."""
    import glob
    import gzip

    events = []
    for f in glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                       recursive=True):
        try:
            with gzip.open(f) as fh:
                t = json.load(fh)
        except (OSError, json.JSONDecodeError, EOFError):
            continue
        events.extend(t.get("traceEvents", []) if isinstance(t, dict)
                      else t)
    if not events:
        return None
    pid_names = {e["pid"]: e.get("args", {}).get("name", "")
                 for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    tid_names = {(e["pid"], e["tid"]): e.get("args", {}).get("name", "")
                 for e in events
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
    xs = [e for e in events
          if e.get("ph") == "X" and "dur" in e and "ts" in e]

    def union_us(evs):
        by_thread: dict[tuple, list] = {}
        for e in evs:
            by_thread.setdefault((e["pid"], e.get("tid")), []).append(
                (float(e["ts"]), float(e["ts"]) + float(e["dur"])))
        total = 0.0
        for ivs in by_thread.values():
            ivs.sort()
            cs, ce = ivs[0]
            for s, t1 in ivs[1:]:
                if s > ce:
                    total += ce - cs
                    cs, ce = s, t1
                else:
                    ce = max(ce, t1)
            total += ce - cs
        return total

    dev_pids = {p for p, n in pid_names.items() if "/device:" in n}
    dev = [e for e in xs if e.get("pid") in dev_pids]
    if dev:
        return union_us(dev), len(dev), "device-track"
    cpu_tids = {k for k, n in tid_names.items() if "XLATfrtCpuClient" in n}
    cpu = [e for e in xs if (e.get("pid"), e.get("tid")) in cpu_tids]
    if cpu:
        return union_us(cpu), len(cpu), "xla-cpu-threadpool"
    return None


def _bench_single_window() -> None:
    """Child process, --single-window mode: depth-1 and depth-4 windows
    through the windowed commit engine, wall p50 + profiler device
    time per depth.  Prints a JSON headline after each depth (the
    parent keeps the LAST line, same salvage contract as the ladder)."""
    _mark("importing jax")
    import tempfile

    import jax

    from apus_tpu.utils.jaxenv import respect_cpu_request
    respect_cpu_request()

    cache = os.environ.get(
        "APUS_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    if cache:
        jax.config.update("jax_compilation_cache_dir",
                          f"{cache}-{jax.default_backend()}")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from jax.sharding import NamedSharding, PartitionSpec as P

    from apus_tpu.core.cid import Cid
    from apus_tpu.ops.commit import (CommitControl,
                                     build_windowed_commit_step)
    from apus_tpu.ops.logplane import host_batch_to_device, make_device_log
    from apus_tpu.ops.mesh import (REPLICA_AXIS, replica_mesh,
                                   replica_sharding)

    _mark("initializing backend")
    backend = jax.default_backend()
    devices = jax.devices()
    _mark(f"backend={backend} devices={devices}")
    cpu = backend == "cpu"
    R, S, SB, B, MD = 5, 4096, 4096, 64, 4    # geometry of the r05 run
    iters = 30 if cpu else 15
    prof_iters = 10 if cpu else 5
    mesh = replica_mesh(R, devices=devices[:1])
    sh = replica_sharding(mesh)
    cid = Cid.initial(R)

    # MD distinct redis-SET-shaped staged batches (round i consumes
    # batch i): the window commits varied payloads, same shape the
    # ladder headline uses.
    sd_np = np.zeros((MD, R, B, SB), np.uint8)
    sm_np = np.zeros((MD, R, B, 4), np.int32)
    for k in range(MD):
        batch_reqs = [
            b"*3\r\n$3\r\nSET\r\n$16\r\nkey:%012d\r\n$64\r\n%s\r\n"
            % (k * B + i, bytes([97 + (k + i) % 26]) * 64)
            for i in range(B)]
        kd, km, _ = host_batch_to_device(batch_reqs, SB, batch_size=B)
        sd_np[k, 0], sm_np[k, 0] = kd, km
    ssh = NamedSharding(mesh, P(None, REPLICA_AXIS))
    sdata = jax.device_put(sd_np, ssh)
    smeta = jax.device_put(sm_np, ssh)
    _mark(f"{MD} staged batches placed on device")

    t_c = time.monotonic()
    step = build_windowed_commit_step(mesh, R, S, SB, B, max_depth=MD)
    devlog = make_device_log(R, S, SB, batch=B, leader=0, term=1,
                             sharding=sh)
    ctrl = CommitControl.from_cid(cid, R, 0, 1, 1)
    end0 = 1
    # Compile + one chained warm dispatch (device-resident donated
    # feedback re-specializes once, same as the ladder).  depth-1 and
    # depth-4 ride this SAME executable: the round count is a runtime
    # scalar, so no per-depth compile is timed below.
    for _ in range(2):
        devlog, commits, rounds_run, ctrl = step(devlog, sdata, smeta,
                                                 ctrl, MD, 1)
        assert int(commits[MD - 1]) == end0 + MD * B
        end0 += MD * B
    _mark(f"windowed engine compiled+warm in {time.monotonic() - t_c:.1f}s")

    windows: dict[str, dict] = {}
    wall1_p50 = None
    for depth in (1, 4):
        walls = []
        for _ in range(iters):
            t0 = time.perf_counter_ns()
            devlog, commits, rounds_run, ctrl = step(devlog, sdata, smeta,
                                                     ctrl, depth, 1)
            # Single-scalar readback: the leader host releases the
            # client on the window's final commit index — part of the
            # round, and what keeps the timing honest on an async
            # tunnel.
            got = int(commits[depth - 1])
            walls.append((time.perf_counter_ns() - t0) / 1e3)
            assert got == end0 + depth * B, (got, end0, depth)
            end0 += depth * B
        walls.sort()
        wall_p50 = walls[len(walls) // 2]
        # Profiler pass: the device-time figure.  block_until_ready
        # (not a scalar readback) serializes dispatches here so the
        # trace holds ONLY the engine's executions — an indexing
        # readback would add its own tiny executable to the trace and
        # pollute the per-execution attribution.
        trace_dir = tempfile.mkdtemp(prefix=f"apus-sw{depth}-")
        with jax.profiler.trace(trace_dir):
            for _ in range(prof_iters):
                devlog, commits, rounds_run, ctrl = step(
                    devlog, sdata, smeta, ctrl, depth, 1)
                jax.block_until_ready(commits)
        end0 += prof_iters * depth * B
        parsed = _trace_device_time(trace_dir)
        if parsed is None:
            dev_us, n_ev, src = None, 0, None
            _mark(f"depth={depth}: profiler trace had no usable device "
                  "signal")
        else:
            total_us, n_ev, src = parsed
            dev_us = total_us / prof_iters
        windows[str(depth)] = {
            "wall_p50_us": round(wall_p50, 2),
            "wall_min_us": round(walls[0], 2),
            "wall_per_round_p50_us": round(wall_p50 / depth, 2),
            "device_time_per_dispatch_us":
                None if dev_us is None else round(dev_us, 2),
            "device_time_per_round_us":
                None if dev_us is None else round(dev_us / depth, 2),
            "device_time_source": src,
            "profiled_dispatches": prof_iters,
            "profiled_events": n_ev,
        }
        dev_txt = "n/a" if dev_us is None else f"{dev_us:.1f}us"
        _mark(f"depth={depth}: wall p50 {wall_p50:.1f}us, "
              f"device {dev_txt} [{src}]")
        if depth == 1:
            wall1_p50 = wall_p50
        # r05's single-dispatch figure is the baseline this mode
        # exists to beat; report the ratio even when the target is
        # missed (and honestly: cross-backend when this run fell back
        # to CPU while r05 rode the tunnel).
        result = {
            "metric": "single_window_commit_p50_latency_batch64_5rep",
            "value": round(wall1_p50, 2),
            "unit": "us",
            "vs_baseline": round(R05_SINGLE_DISPATCH_US / wall1_p50, 2),
            "detail": {
                "backend": backend,
                "mode": "single_window",
                "engine": "build_windowed_commit_step",
                "max_depth": MD,
                "windows": windows,
                "r05_single_dispatch_round_p50_us": R05_SINGLE_DISPATCH_US,
                "r05_backend": "tpu(axon-tunnel)",
                "speedup_vs_r05_single_dispatch":
                    round(R05_SINGLE_DISPATCH_US / wall1_p50, 2),
                "batch": B, "replicas": R, "slot_bytes": SB,
                "n_slots": S,
                "baseline_round_us": BASELINE_ROUND_US,
            },
        }
        print(json.dumps(result), flush=True)


def _bench_throughput() -> None:
    """--throughput mode: the replicated commits/sec headline (the
    BASELINE north star's "commits/sec (Redis SET)" axis, which PR 1's
    latency work did not touch).  Drives P concurrent clients against a
    LIVE LocalCluster over real sockets in four configurations:

      serial      — one op per wire roundtrip per client (the pre-ISSUE-3
                    path; the baseline denominator);
      pipelined   — ApusClient.pipeline, 64-deep in-flight window
                    (client pipelining + server burst admission +
                    group-commit + window-granular commit wakes);
      pipelined_nogroup — same client but max_batch=1 on the cluster, so
                    every replication write carries ONE entry: isolates
                    the group-commit contribution;
      GETs with/without the read lease — pipelined reads, counting how
                    many were served from leader-local state vs paying
                    the read-index majority round.

    The serial/pipelined pair is measured TWICE: raw loopback, and
    under an EMULATED client-link RTT (one client-side sleep per wire
    roundtrip, applied identically to both variants — the
    redis-benchmark -P methodology).  On this one-core box raw-loopback
    serial is CPU-bound, not latency-bound (16 concurrent serial
    writers already share commit windows via the cross-connection
    group-commit drain), so the raw ratio understates the architecture;
    the RTT pair shows the regime remote clients actually occupy, where
    a serial client pays the link RTT per op and a pipelined one per
    window.  Both numbers are reported, clearly labeled.

    Pure host path (no JAX import): the numbers measure the replicated
    wire/daemon/commit stack itself.  Env knobs: APUS_TPUT_CLIENTS (16),
    APUS_TPUT_SECONDS (2.0), APUS_TPUT_REPLICAS (3), APUS_TPUT_WINDOW
    (64), APUS_TPUT_RTT_MS (10.0 — the emulated-RTT pair's link RTT; 0
    skips that pair).  Prints ONE JSON headline (value = raw pipelined
    SET ops/sec; vs_baseline = pipelined/serial under the emulated
    RTT, the ISSUE 3 acceptance axis)."""
    import dataclasses
    import threading

    from apus_tpu.runtime.client import ApusClient, probe_status
    from apus_tpu.runtime.cluster import LocalCluster
    from apus_tpu.utils.config import ClusterSpec

    P = int(os.environ.get("APUS_TPUT_CLIENTS", "16"))
    seconds = float(os.environ.get("APUS_TPUT_SECONDS", "2.0"))
    R = int(os.environ.get("APUS_TPUT_REPLICAS", "3"))
    W = int(os.environ.get("APUS_TPUT_WINDOW", "64"))
    rtt = float(os.environ.get("APUS_TPUT_RTT_MS", "10.0")) / 1e3
    base_spec = ClusterSpec(hb_period=0.005, hb_timeout=0.030,
                            elect_low=0.050, elect_high=0.150)

    def flr_sum(peers):
        tot = 0
        for p in peers:
            st = probe_status(p, timeout=1.0) or {}
            tot += st.get("flr_local_reads", 0) or 0
        return tot

    def drive(cluster, pipelined: bool, reads: bool = False,
              link_rtt: float = 0.0, read_policy: str = "leader"):
        """P worker threads for ``seconds``; returns (ops, elapsed,
        leader-counter deltas).  ``link_rtt`` adds one client-side
        sleep per wire roundtrip — serial pays it per OP, pipelined per
        WINDOW — emulating a remote client's link identically for both
        shapes.  ``read_policy="spread"`` routes GETs across all
        replicas (follower read leases)."""
        leader = cluster.wait_for_leader(30.0)
        peers = list(cluster.spec.peers)
        with ApusClient(peers, timeout=20.0,
                        read_policy=read_policy) as warm:
            warm.put(b"warm", b"w")
            if reads:
                warm.get(b"warm")
        st0 = probe_status(peers[leader.idx], timeout=2.0) or {}
        flr0 = flr_sum(peers) if reads else 0
        done = [0] * P
        stop_at = time.monotonic() + seconds
        fails = [0] * P

        def worker(w: int):
            with ApusClient(peers, timeout=30.0,
                            read_policy=read_policy) as cl:
                if reads:
                    # Pin the leader before timing: a fresh client's
                    # first probe can land on a follower, and under
                    # follower read leases that follower would SERVE
                    # the "leader-only" baseline's reads — the pin
                    # keeps the leader row leader-routed (spread reads
                    # route by rotor regardless).
                    cl.put(b"warm", b"w")
                i = 0
                while time.monotonic() < stop_at:
                    try:
                        if reads and pipelined:
                            cl.pipeline_gets([b"warm"] * W)
                            done[w] += W
                        elif reads:
                            cl.get(b"warm")
                            done[w] += 1
                        elif pipelined:
                            cl.pipeline_puts(
                                [(b"k%d-%d-%d" % (w, i, j), b"v" * 64)
                                 for j in range(W)])
                            done[w] += W
                        else:
                            cl.put(b"k%d-%d" % (w, i), b"v" * 64)
                            done[w] += 1
                        i += 1
                        if link_rtt:
                            time.sleep(link_rtt)
                    except (TimeoutError, RuntimeError):
                        fails[w] += 1
                        if fails[w] > 3:
                            return

        t0 = time.monotonic()
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(P)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        st1 = probe_status(peers[leader.idx], timeout=2.0) or {}
        delta = {k: st1.get(k, 0) - st0.get(k, 0)
                 for k in ("lease_reads", "readindex_verifies",
                           "drain_windows", "drain_entries",
                           "repl_windows")}
        if reads:
            delta["flr_local_reads"] = flr_sum(peers) - flr0
        return sum(done), elapsed, delta

    results: dict[str, dict] = {}

    def run_variant(cluster, name, pipelined, reads=False, link_rtt=0.0,
                    read_policy="leader"):
        ops, elapsed, delta = drive(cluster, pipelined, reads=reads,
                                    link_rtt=link_rtt,
                                    read_policy=read_policy)
        results[name] = {
            "ops_per_sec": round(ops / elapsed, 1),
            "ops": ops, "elapsed_s": round(elapsed, 3),
            "counters": delta,
        }
        _mark(f"  {name}: {results[name]['ops_per_sec']:.0f} ops/s")
        return results[name]

    _mark(f"throughput: {R}-replica LocalCluster, {P} clients, "
          f"{seconds:.1f}s per variant, emulated link rtt "
          f"{rtt * 1e3:.1f}ms")
    with LocalCluster(R, spec=dataclasses.replace(base_spec)) as c:
        run_variant(c, "serial_raw", pipelined=False)
        run_variant(c, "pipelined_raw", pipelined=True)
        if rtt > 0:
            run_variant(c, "serial_rtt", pipelined=False, link_rtt=rtt)
            run_variant(c, "pipelined_rtt", pipelined=True, link_rtt=rtt)
        g = run_variant(c, "gets_lease", pipelined=True, reads=True)
        _mark(f"    (lease_reads +{g['counters']['lease_reads']}, "
              f"verifies +{g['counters']['readindex_verifies']})")
        gf = run_variant(c, "gets_follower_raw", pipelined=True,
                         reads=True, read_policy="spread")
        _mark(f"    (flr_local_reads "
              f"+{gf['counters'].get('flr_local_reads', 0)})")

    # FOLLOWER-READ SCALE ROW (the ROADMAP read scale-out target):
    # leader-only vs spread GETs under a per-replica read
    # service-capacity gate (APUS_READ_SVC_US) — on this one-core box
    # every replica timeshares one core, so raw aggregate throughput
    # cannot exceed ~1x no matter where reads are served; the gate
    # emulates the multi-core deployment the architecture targets
    # (each replica owning a core's worth of read service), identically
    # for both rows, exactly like the emulated-RTT pair above emulates
    # a remote link.  The raw (ungated) pair is reported alongside.
    svc_ms = float(os.environ.get("APUS_TPUT_SVC_MS", "1.0"))
    if svc_ms > 0:
        os.environ["APUS_READ_SVC_US"] = str(int(svc_ms * 1000))
        try:
            with LocalCluster(R, spec=dataclasses.replace(
                    base_spec)) as c:
                run_variant(c, "gets_leader_svc", pipelined=True,
                            reads=True)
                gs = run_variant(c, "gets_follower_svc",
                                 pipelined=True, reads=True,
                                 read_policy="spread")
                _mark(f"    (flr_local_reads "
                      f"+{gs['counters'].get('flr_local_reads', 0)})")
        finally:
            os.environ.pop("APUS_READ_SVC_US", None)

    with LocalCluster(R, spec=dataclasses.replace(
            base_spec, max_batch=1)) as c:
        run_variant(c, "pipelined_nogroup", pipelined=True)

    with LocalCluster(R, spec=dataclasses.replace(
            base_spec, read_lease=False)) as c:
        run_variant(c, "gets_readindex", pipelined=True, reads=True)

    # -- NATIVE DATA PLANE rows (ISSUE 13) -----------------------------
    # Two methodologies, both apples-to-apples:
    #   *_native      — the EXACT Python-client variants above, against
    #                   a native-plane cluster (client CPU shared, so
    #                   on one box this understates the server gain);
    #   ldgen_*       — the native pipelined load generator
    #                   (dataplane.loadgen, GIL-released) against BOTH
    #                   planes: the server data plane's capacity
    #                   without a Python-client bottleneck.  raw and
    #                   RTT-gated rows for each.
    from apus_tpu.parallel.native_plane import load_extension
    _ext = load_extension()
    native_counters = {}

    def ldgen(cluster, name, op, link_rtt=0.0, threads=4):
        import threading as _th
        leader = cluster.wait_for_leader(30.0)
        host, port = leader.server.addr
        # Pre-populate the key pool (and for GET rows, settle apply)
        # so GETs measure real lookups.
        _ext.loadgen(host, port, seconds=0.3, window=W, op="put",
                     nkeys=256, vlen=64, prefix="nlg")
        time.sleep(0.1)
        out = [None] * threads

        def drive_one(i):
            out[i] = _ext.loadgen(host, port, seconds=seconds,
                                  window=W, op=op, nkeys=256, vlen=64,
                                  rtt_us=int(link_rtt * 1e6),
                                  prefix="nlg")

        ts = [_th.Thread(target=drive_one, args=(i,))
              for i in range(threads)]
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        elapsed = max(time.monotonic() - t0, 1e-6)
        ok = sum(r["ok"] for r in out if r)
        fails = sum(r["fails"] + r["not_leader"] for r in out if r)
        results[name] = {"ops_per_sec": round(ok / elapsed, 1),
                         "ops": ok, "fails": fails,
                         "elapsed_s": round(elapsed, 3)}
        _mark(f"  {name}: {results[name]['ops_per_sec']:.0f} ops/s"
              + (f" ({fails} fails)" if fails else ""))

    if _ext is not None:
        with LocalCluster(R, spec=dataclasses.replace(base_spec)) as c:
            ldgen(c, "ldgen_put_python", "put")
            ldgen(c, "ldgen_get_python", "get")
            if rtt > 0:
                ldgen(c, "ldgen_put_python_rtt", "put", link_rtt=rtt)
                ldgen(c, "ldgen_get_python_rtt", "get", link_rtt=rtt)
        with LocalCluster(R, spec=dataclasses.replace(
                base_spec, native_plane=True)) as c:
            run_variant(c, "serial_raw_native", pipelined=False)
            run_variant(c, "pipelined_raw_native", pipelined=True)
            if rtt > 0:
                run_variant(c, "pipelined_rtt_native", pipelined=True,
                            link_rtt=rtt)
            run_variant(c, "gets_lease_native", pipelined=True,
                        reads=True)
            ldgen(c, "ldgen_put_native", "put")
            ldgen(c, "ldgen_get_native", "get")
            if rtt > 0:
                ldgen(c, "ldgen_put_native_rtt", "put", link_rtt=rtt)
                ldgen(c, "ldgen_get_native_rtt", "get", link_rtt=rtt)
            ld = c.wait_for_leader(10.0)
            if ld.native is not None:
                native_counters = ld.native.plane.counters()
    else:
        _mark("  native rows SKIPPED (extension not built: "
              "make -C native dataplane)")

    def ops(name):
        return results[name]["ops_per_sec"] if name in results else None

    piped_raw = ops("pipelined_raw")
    serial_raw = ops("serial_raw") or 1.0
    # The acceptance axis (>= 5x is the ISSUE 3 bar): pipelined vs
    # serial with the SAME emulated client link.  Falls back to the
    # raw-loopback pair when the RTT pair was skipped.
    num = ops("pipelined_rtt") if rtt > 0 else piped_raw
    den = (ops("serial_rtt") if rtt > 0 else serial_raw) or 1.0
    speedup = round(num / den, 2)
    dw = results["pipelined_raw"]["counters"]["drain_windows"] or 1
    result = {
        "metric": f"pipelined_set_throughput_{P}c_{R}rep",
        "value": piped_raw,
        "unit": "ops/s",
        "vs_baseline": speedup,
        "detail": {
            "mode": "throughput",
            "replicas": R, "clients": P, "window": W,
            "seconds_per_variant": seconds,
            "emulated_link_rtt_ms": rtt * 1e3,
            "pipelined_vs_serial": speedup,
            "speedup_regime": ("emulated_rtt" if rtt > 0
                               else "raw_loopback"),
            "serial_raw_ops_per_sec": serial_raw,
            "pipelined_raw_ops_per_sec": piped_raw,
            "raw_loopback_speedup": round(piped_raw / serial_raw, 2),
            "serial_rtt_ops_per_sec": ops("serial_rtt"),
            "pipelined_rtt_ops_per_sec": ops("pipelined_rtt"),
            "pipelined_nogroup_ops_per_sec": ops("pipelined_nogroup"),
            "group_commit_gain": round(
                piped_raw / (ops("pipelined_nogroup") or 1.0), 2),
            "entries_per_drain_window": round(
                results["pipelined_raw"]["counters"]["drain_entries"]
                / dw, 1),
            "gets_lease_ops_per_sec": ops("gets_lease"),
            "gets_readindex_ops_per_sec": ops("gets_readindex"),
            "lease_gain": round(
                (ops("gets_lease") or 0.0)
                / (ops("gets_readindex") or 1.0), 2),
            # Follower-read scale-out (ROADMAP: 3-replica GETs >= 2.5x
            # leader-only).  The _svc pair runs under the per-replica
            # read service gate (emulated_read_svc_ms, identical for
            # both rows — see note); the _raw follower row shows the
            # ungated single-core reality alongside.
            "gets_follower_raw_ops_per_sec": ops("gets_follower_raw"),
            "gets_leader_svc_ops_per_sec": ops("gets_leader_svc"),
            "gets_follower_svc_ops_per_sec": ops("gets_follower_svc"),
            "emulated_read_svc_ms": svc_ms,
            # Native data plane (ISSUE 13): Python-client rows against
            # the native-plane cluster, native-loadgen rows against
            # BOTH planes (raw + RTT-gated), and the gain axes.  The
            # ldgen_* pairs are the server-capacity comparison (same
            # native client against both planes — the clients above
            # share the box's CPU with the server, understating it).
            "pipelined_raw_native_ops_per_sec":
                ops("pipelined_raw_native"),
            "serial_raw_native_ops_per_sec": ops("serial_raw_native"),
            "pipelined_rtt_native_ops_per_sec":
                ops("pipelined_rtt_native"),
            "gets_lease_native_ops_per_sec": ops("gets_lease_native"),
            "ldgen_put_python_ops_per_sec": ops("ldgen_put_python"),
            "ldgen_put_native_ops_per_sec": ops("ldgen_put_native"),
            "ldgen_get_python_ops_per_sec": ops("ldgen_get_python"),
            "ldgen_get_native_ops_per_sec": ops("ldgen_get_native"),
            "ldgen_put_python_rtt_ops_per_sec":
                ops("ldgen_put_python_rtt"),
            "ldgen_put_native_rtt_ops_per_sec":
                ops("ldgen_put_native_rtt"),
            "ldgen_get_python_rtt_ops_per_sec":
                ops("ldgen_get_python_rtt"),
            "ldgen_get_native_rtt_ops_per_sec":
                ops("ldgen_get_native_rtt"),
            "native_pipelined_gain_pyclient": round(
                (ops("pipelined_raw_native") or 0.0)
                / (piped_raw or 1.0), 2),
            "native_put_gain_ldgen": round(
                (ops("ldgen_put_native") or 0.0)
                / (ops("ldgen_put_python") or 1.0), 2),
            "native_get_gain_ldgen": round(
                (ops("ldgen_get_native") or 0.0)
                / (ops("ldgen_get_python") or 1.0), 2),
            "native_counters": native_counters or None,
            "follower_read_gain": round(
                (ops("gets_follower_svc") or 0.0)
                / (ops("gets_leader_svc") or 1.0), 2),
            "follower_read_gain_raw": round(
                (ops("gets_follower_raw") or 0.0)
                / (ops("gets_lease") or 1.0), 2),
            "variants": results,
            # Every SET is one log entry here: entries/sec == ops/sec.
            "entries_per_sec": piped_raw,
            "commits_per_sec": piped_raw,
            "note": ("serial/pipelined _rtt rows add one client-side "
                     "sleep of emulated_link_rtt_ms per wire roundtrip "
                     "to BOTH shapes (redis-benchmark -P methodology); "
                     "on this 1-core box raw-loopback serial is "
                     "CPU-bound, not roundtrip-bound, so the raw ratio "
                     "understates the pipelining win remote clients "
                     "see.  gets_*_svc rows gate read service at "
                     "emulated_read_svc_ms per read PER REPLICA "
                     "(APUS_READ_SVC_US, identical gate both rows): "
                     "all replicas timeshare this box's one core, so "
                     "ungated aggregate read throughput is core-bound "
                     "wherever reads are served — the gate emulates "
                     "the multi-core deployment where each replica "
                     "owns a core, which is the regime the follower-"
                     "read architecture targets; follower_read_gain "
                     "is the 3-replica-spread vs leader-only ratio "
                     "under that gate, follower_read_gain_raw the "
                     "ungated single-core one."),
        },
    }
    print(json.dumps(result), flush=True)


def _bench_throughput_groups(groups_list) -> None:
    """--throughput --groups mode: the Multi-Raft aggregate-throughput
    ladder (ISSUE 10 acceptance axis).  For each G in ``groups_list``
    drives P pipelined writers against a LIVE LocalCluster sharded into
    G consensus groups, with:

    - the GROUP-MAJOR device plane ON (runtime.group_plane): the
      dispatch-amortization counters (`dev_group_major_windows`,
      `dev_groups_per_dispatch`) are the acceptance evidence that
      device work is batched across groups — G=1 runs the SAME engine
      (group_major=True) so the ladder is apples-to-apples;
    - a PER-GROUP write service-capacity gate (APUS_WRITE_SVC_US,
      default APUS_TPUT_WSVC_MS=1.0 ms/write): on this one-core box
      every group's leader timeshares one core, so raw aggregate
      write throughput cannot exceed ~1x wherever the keyspace is
      sharded; the gate emulates the deployment the architecture
      targets — each group's leader owning a core's worth of write
      service — identically at every rung (the exact methodology of
      the PR 9 follower-read APUS_READ_SVC_US gate and the PR 3
      emulated-RTT pair, clearly labeled).

    Aggregate ops/s must scale near-linearly to G=4 (>= 3x the G=1
    rung per the ROADMAP gate); the recompile sentinel must read zero
    across every rung.  Prints ONE JSON headline (value = G=4
    aggregate; vs_baseline = G4/G1 scaling)."""
    import dataclasses
    import threading

    from apus_tpu.runtime.client import ApusClient, probe_status
    from apus_tpu.runtime.cluster import LocalCluster
    from apus_tpu.utils.config import ClusterSpec

    P = int(os.environ.get("APUS_TPUT_CLIENTS", "16"))
    seconds = float(os.environ.get("APUS_TPUT_SECONDS", "3.0"))
    R = int(os.environ.get("APUS_TPUT_REPLICAS", "3"))
    W = int(os.environ.get("APUS_TPUT_WINDOW", "64"))
    wsvc_ms = float(os.environ.get("APUS_TPUT_WSVC_MS", "1.5"))
    base_spec = ClusterSpec(hb_period=0.005, hb_timeout=0.030,
                            elect_low=0.050, elect_high=0.150)
    rungs: dict[str, dict] = {}
    os.environ["APUS_WRITE_SVC_US"] = str(int(wsvc_ms * 1000))
    try:
        for G in groups_list:
            _mark(f"groups={G}: {R}-replica LocalCluster, {P} clients, "
                  f"{seconds:.1f}s, write-svc {wsvc_ms:.2f} ms/op/group,"
                  f" group-major device plane on")
            with LocalCluster(
                    R, spec=dataclasses.replace(base_spec, groups=G),
                    groups=G, device_plane=True, device_batch=16,
                    group_major=True) as c:
                c.wait_for_group_leaders(timeout=30.0)
                runner = c.device_runner
                snap0 = runner.metrics.snapshot()
                peers = list(c.spec.peers)
                with ApusClient(peers, groups=G, timeout=30.0,
                                attempt_timeout=10.0) as warm:
                    warm.pipeline_puts([(b"warm%d" % i, b"w")
                                        for i in range(4 * G)])
                done = [0] * P
                fails = [0] * P
                stop_at = time.monotonic() + seconds

                def worker(w, peers=peers, G=G, stop_at=stop_at):
                    # One GROUP per burst, rotating per client
                    # (explicit-gid routing): the shape real sharded
                    # workloads pipeline in (redis-cluster clients
                    # batch per slot owner) — each burst is one
                    # full-window sub-pipeline, groups evenly loaded
                    # by the rotation, and EVERY rung (G=1 included)
                    # runs the identical client shape.
                    from apus_tpu.models.kvs import encode_put
                    from apus_tpu.runtime.client import OP_CLT_WRITE
                    # attempt_timeout ABOVE the worst-case gate queue
                    # (16 clients x 96 ms of gated service per burst):
                    # a 2 s per-attempt cap would misread the queue as
                    # a dead peer and the retry re-enqueues the burst
                    # behind the same gate — a self-amplifying cascade.
                    with ApusClient(peers, groups=G, timeout=30.0,
                                    attempt_timeout=10.0) as cl:
                        i = 0
                        while time.monotonic() < stop_at:
                            gid = (w + i) % G
                            try:
                                cl.pipeline(
                                    [(OP_CLT_WRITE,
                                      encode_put(b"k%d-%d-%d"
                                                 % (w, i, j),
                                                 b"v" * 64), gid)
                                     for j in range(W)])
                                done[w] += W
                                i += 1
                            except (TimeoutError, RuntimeError):
                                fails[w] += 1
                                if fails[w] > 3:
                                    return

                t0 = time.monotonic()
                threads = [threading.Thread(target=worker, args=(w,))
                           for w in range(P)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                elapsed = time.monotonic() - t0
                time.sleep(0.3)          # let trailing dispatches land
                snap1 = runner.metrics.snapshot()

                def cdelta(name):
                    a = (snap0.get(name) or {}).get("value", 0)
                    b = (snap1.get(name) or {}).get("value", 0)
                    return b - a

                gpd = snap1.get("dev_groups_per_dispatch") or {}
                from apus_tpu.runtime.device_plane import \
                    unexpected_compiles
                dispatches = cdelta("dev_group_major_windows")
                windows = cdelta("dev_rounds")
                # Leader-side per-group commit evidence.
                leaders_of = {}
                for addr in peers:
                    st = probe_status(addr, timeout=2.0) or {}
                    for gid, gv in (st.get("groups")
                                    or {"0": st}).items():
                        if gv.get("is_leader"):
                            leaders_of[gid] = st.get("idx")
                rungs[str(G)] = {
                    "ops_per_sec": round(sum(done) / elapsed, 1),
                    "ops": sum(done),
                    "elapsed_s": round(elapsed, 3),
                    "client_failures": sum(fails),
                    "group_leaders": leaders_of,
                    "dev_group_major_windows": dispatches,
                    "dev_windows": windows,
                    "dispatches_per_window": round(
                        dispatches / windows, 3) if windows else None,
                    "dev_groups_per_dispatch_p50": gpd.get("p50"),
                    "dev_groups_per_dispatch_mean": round(
                        gpd.get("sum", 0) / gpd.get("count", 1), 3)
                    if gpd.get("count") else None,
                    "dev_groups_per_dispatch_hist": gpd.get("buckets"),
                    "multi_group_dispatches": sum(
                        v for k, v in (gpd.get("buckets")
                                       or {}).items() if int(k) >= 2),
                    "dev_quorum_fail_rounds": cdelta(
                        "dev_quorum_fail_rounds"),
                    "recompile_sentinel": unexpected_compiles(),
                }
                _mark(f"  groups={G}: "
                      f"{rungs[str(G)]['ops_per_sec']:.0f} ops/s, "
                      f"{dispatches} group-major dispatches / "
                      f"{windows} windows, groups/dispatch p50 "
                      f"{gpd.get('p50')}")
    finally:
        os.environ.pop("APUS_WRITE_SVC_US", None)

    # GROUP-MAJOR EVIDENCE phase: a dedicated UNGATED saturation run at
    # 8 groups over the same 3 daemons (pigeonhole: every daemon leads
    # >= 2 groups), so every driver pass has multiple groups with
    # backlog — the regime the dispatch-amortization counters gate on.
    # The throughput ladder above is gate-paced with leaders spread
    # across daemons (the load-spreading the sharding exists for), so
    # its per-dispatch pairing depends on leader placement; this phase
    # pins the amortization claim itself: groups/dispatch p50 > 1.
    EG = int(os.environ.get("APUS_TPUT_EVIDENCE_GROUPS", "8"))
    evidence = None
    with LocalCluster(
            R, spec=dataclasses.replace(base_spec, groups=EG),
            groups=EG, device_plane=True, device_batch=16,
            group_major=True) as c:
        c.wait_for_group_leaders(timeout=30.0)
        runner = c.device_runner
        peers = list(c.spec.peers)
        snap0 = runner.metrics.snapshot()
        estop = time.monotonic() + 2.0

        def esat(w):
            with ApusClient(peers, groups=EG, timeout=30.0,
                            attempt_timeout=10.0) as cl:
                i = 0
                while time.monotonic() < estop:
                    try:
                        cl.pipeline_puts(
                            [(b"e%d-%d-%d" % (w, i, j), b"v" * 64)
                             for j in range(W)])
                        i += 1
                    except (TimeoutError, RuntimeError):
                        return

        eth = [threading.Thread(target=esat, args=(w,))
               for w in range(P)]
        for t in eth:
            t.start()
        for t in eth:
            t.join()
        time.sleep(0.3)
        snap1 = runner.metrics.snapshot()
        h0 = snap0.get("dev_groups_per_dispatch") or {}
        h1 = snap1.get("dev_groups_per_dispatch") or {}
        b0 = h0.get("buckets") or {}
        b1 = h1.get("buckets") or {}
        db = {k: b1.get(k, 0) - b0.get(k, 0) for k in set(b0) | set(b1)}
        db = {k: v for k, v in db.items() if v > 0}
        count = sum(db.values())
        total = h1.get("sum", 0) - h0.get("sum", 0)
        # Exact p50 CLASS from the log2 buckets: bucket "1" is exactly
        # 1 group per dispatch, "2" is 2-3, "3" is 4-7.
        p50_ge2 = None
        if count:
            acc = 0
            for k in sorted(db, key=int):
                acc += db[k]
                if acc * 2 >= count:
                    p50_ge2 = int(k) >= 2
                    break
        per_daemon = {
            d.idx: {"dispatches": d.device_driver.stats.get(
                        "dispatches", 0),
                    "group_windows": d.device_driver.stats.get(
                        "group_windows", 0)}
            for d in c.live()}
        from apus_tpu.runtime.device_plane import unexpected_compiles
        evidence = {
            "groups": EG,
            "dispatches": count,
            "group_windows_carried": total,
            "mean_groups_per_dispatch": round(total / count, 3)
            if count else None,
            "p50_multi_group": p50_ge2,
            "buckets": db,
            "per_daemon": per_daemon,
            "recompile_sentinel": unexpected_compiles(),
        }
        _mark(f"  group-major evidence ({EG} groups, ungated): "
              f"{count} dispatches carrying {total} group-windows, "
              f"mean {evidence['mean_groups_per_dispatch']}, p50 "
              f"multi-group: {p50_ge2}")

    g1 = rungs.get("1", {}).get("ops_per_sec") or 1.0
    top = str(max(int(g) for g in rungs))
    agg = rungs[top]["ops_per_sec"]
    scaling = round(agg / g1, 2)
    result = {
        "metric": f"multigroup_set_throughput_{P}c_{R}rep",
        "value": agg,
        "unit": "ops/s",
        "vs_baseline": scaling,
        "detail": {
            "mode": "throughput_groups",
            "replicas": R, "clients": P, "window": W,
            "seconds_per_rung": seconds,
            "groups_ladder": sorted(int(g) for g in rungs),
            "emulated_write_svc_ms": wsvc_ms,
            "scaling_vs_1group": {
                g: round(r["ops_per_sec"] / g1, 2)
                for g, r in rungs.items()},
            "rungs": rungs,
            "group_major_evidence": evidence,
            "note": ("every rung runs the SAME per-group write "
                     "service-capacity gate (APUS_WRITE_SVC_US, one "
                     "gate per group at its leader): all groups "
                     "timeshare this box's one core, so ungated "
                     "aggregate write throughput is core-bound "
                     "wherever the keyspace is sharded — the gate "
                     "emulates the multi-core deployment where each "
                     "group's leader owns a core, which is the regime "
                     "Multi-Raft sharding targets (same methodology "
                     "as the PR 9 read-svc gate).  The group-major "
                     "device plane runs at every rung (G=1 included, "
                     "group_major=True) so dispatch-amortization "
                     "counters are apples-to-apples."),
        },
    }
    print(json.dumps(result), flush=True)


def _bench_devices(devices_list) -> None:
    """--devices mode: the MULTI-DEVICE group-window throughput ladder
    (ISSUE 14 acceptance axis).  For each device count D the 4-group
    group-major engine runs on a real ``(group, replica)`` mesh of D
    virtual CPU devices (``--xla_force_host_platform_device_count``,
    the local stand-in for a TPU pod slice) and the ASYNC dispatch
    beat drives back-to-back 4-group windows through it — dispatch
    window N+1, adopt window N at the fence — for a fixed wall budget.

    GATE METHODOLOGY (the BENCH_r10 write-svc-gate methodology, moved
    to the device axis): on this one-core box D virtual devices
    timeshare one core, so raw wall cannot scale with D wherever the
    groups are sharded.  A PER-DEVICE window service gate
    (APUS_DEV_SVC_MS per group-window, default 3.0 ms) emulates the
    deployment the mesh targets — each device owning a chip's worth of
    window execution: after every dispatch the loop sleeps
    ``gate * (groups landing on the BUSIEST device shard)``, so groups
    sharded across devices pay their window service in parallel and
    groups folded onto one device pay it serially.  The gate is
    identical at every rung and clearly labeled; the UNGATED dispatch
    overhead is reported alongside (it is the flat-ish-wall claim the
    perfgate budget pins).

    Aggregate group-windows/s at D=4 must be >= 2.5x the D=1 rung
    (ISSUE 14 acceptance); the recompile sentinel must read zero at
    every rung.  Prints ONE JSON headline (value = top-rung aggregate;
    vs_baseline = top/D1 scaling)."""
    need = max(devices_list)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={need}").strip()
    import statistics

    import jax

    jax.config.update("jax_platforms", "cpu")
    from apus_tpu.core.cid import Cid
    from apus_tpu.core.log import LogEntry
    from apus_tpu.core.types import EntryType
    from apus_tpu.runtime.device_plane import unexpected_compiles
    from apus_tpu.runtime.group_plane import GroupDeviceRunner

    G = int(os.environ.get("APUS_DEV_GROUPS", "4"))
    R = int(os.environ.get("APUS_DEV_REPLICAS", "3"))
    B = int(os.environ.get("APUS_DEV_BATCH", "16"))
    seconds = float(os.environ.get("APUS_DEV_SECONDS", "3.0"))
    gate_ms = float(os.environ.get("APUS_DEV_SVC_MS", "3.0"))
    if len(jax.devices()) < need:
        print(json.dumps({
            "metric": f"multidevice_group_window_throughput_{G}g",
            "value": None, "unit": "group-windows/s",
            "vs_baseline": 0.0,
            "detail": {"mode": "devices",
                       "error": f"jax hosts {len(jax.devices())} "
                                f"devices, ladder needs {need}"},
        }), flush=True)
        return
    cid = Cid.initial(R)
    live = set(range(R))
    rungs: dict[str, dict] = {}
    for D in devices_list:
        _mark(f"devices={D}: {G}-group group-major runner, async beat,"
              f" {seconds:.1f}s, per-device window svc gate "
              f"{gate_ms:.1f} ms")
        base_compiles = unexpected_compiles()
        runner = GroupDeviceRunner(
            n_groups=G, n_replicas=R, n_slots=32 * B, slot_bytes=1024,
            batch=B, max_depth=4, devices=jax.devices()[:D])
        gens = [runner.reset_group(g, leader=0, term=1, first_idx=1)
                for g in range(G)]
        assert all(g is not None for g in gens)
        # Busiest shard: how many of the G groups one device executes.
        busiest = G // runner.group_axis_size
        cursors = [1] * G
        payload = b"x" * 64

        def window(g, cursors=cursors, gens=gens):
            first = cursors[g]
            es = [LogEntry(idx=first + j, term=1, req_id=j + 1,
                           clt_id=1, type=EntryType.CSM, head=0,
                           data=payload) for j in range(B)]
            return (g, gens[g], first, es, cid, live)

        prev = prev_deadline = None
        gw = dispatches = 0
        walls = []
        t0 = time.monotonic()
        stop_at = t0 + seconds
        gate_s = gate_ms / 1e3 * busiest
        # The gate models the DEVICE being busy: a window's emulated
        # completion is gate_s after its shards start executing (=
        # dispatch time, or the previous window's completion if the
        # device is still busy — consecutive windows on one device
        # serialize).  The host stages the NEXT window while the
        # emulated device runs, and the ADOPTION FENCE sleeps only
        # the remainder — the async-beat overlap this ladder exists
        # to measure.
        dev_free_at = time.monotonic()
        while time.monotonic() < stop_at:
            t_d = time.perf_counter()
            work = [window(g) for g in range(G)]
            win = runner.dispatch_groups(work)
            assert win is not None
            for g in range(G):
                cursors[g] += B
            walls.append((time.perf_counter() - t_d) * 1e6)
            dev_free_at = max(dev_free_at, time.monotonic()) + gate_s
            if prev is not None:
                left = prev_deadline - time.monotonic()
                if left > 0:
                    time.sleep(left)        # the adoption fence
                runner.adopt_window(prev)
            prev, prev_deadline = win, dev_free_at
            gw += G
            dispatches += 1
        if prev is not None:
            left = prev_deadline - time.monotonic()
            if left > 0:
                time.sleep(left)
            runner.adopt_window(prev)
        elapsed = time.monotonic() - t0
        snap = runner.metrics.snapshot()
        sw = snap.get("dev_staging_wait_us") or {}
        rungs[str(D)] = {
            "group_windows_per_sec": round(gw / elapsed, 1),
            "group_windows": gw,
            "dispatches": dispatches,
            "elapsed_s": round(elapsed, 3),
            "mesh": {"group": runner.group_axis_size,
                     "replica": runner.n_devices
                     // runner.group_axis_size},
            "busiest_shard_groups": busiest,
            "gated_window_svc_ms": round(gate_ms * busiest, 3),
            "dispatch_overhead_p50_us": round(
                statistics.median(walls), 1) if walls else None,
            "wall_per_group_window_us": round(
                elapsed * 1e6 / gw, 1) if gw else None,
            "groups_per_dispatch": round(gw / dispatches, 3)
            if dispatches else None,
            "async_overlap_windows": snap.get(
                "dev_async_overlap_windows", {}).get("value", 0),
            "staging_wait_p50_us": sw.get("p50"),
            "recompile_sentinel": unexpected_compiles()
            - base_compiles,
        }
        _mark(f"  devices={D}: "
              f"{rungs[str(D)]['group_windows_per_sec']:.0f} "
              f"group-windows/s (busiest shard {busiest} groups, "
              f"dispatch overhead p50 "
              f"{rungs[str(D)]['dispatch_overhead_p50_us']:.0f} us, "
              f"sentinel {rungs[str(D)]['recompile_sentinel']})")
        del runner

    d1 = rungs.get("1", {}).get("group_windows_per_sec") or 1.0
    top = str(max(int(d) for d in rungs))
    agg = rungs[top]["group_windows_per_sec"]
    result = {
        "metric": f"multidevice_group_window_throughput_{G}g",
        "value": agg,
        "unit": "group-windows/s",
        "vs_baseline": round(agg / d1, 2),
        "detail": {
            "mode": "devices",
            "groups": G, "replicas": R, "batch": B,
            "devices_ladder": sorted(int(d) for d in rungs),
            "emulated_device_window_svc_ms": gate_ms,
            "seconds_per_rung": seconds,
            "scaling_vs_1device": {
                d: round(r["group_windows_per_sec"] / d1, 2)
                for d, r in rungs.items()},
            "rungs": rungs,
            "note": ("every rung pays the SAME per-device window "
                     "service gate (APUS_DEV_SVC_MS x groups on the "
                     "busiest device shard): the emulated device is "
                     "busy for that long from dispatch, the host "
                     "stages the NEXT window underneath it, and the "
                     "adoption fence sleeps only the remainder — the "
                     "async-beat overlap is the thing measured.  All "
                     "virtual devices timeshare this box's one core, "
                     "so ungated wall cannot scale with D; the gate "
                     "emulates the deployment the mesh targets, each "
                     "device owning a chip's worth of window "
                     "execution (the BENCH_r10 write-svc methodology "
                     "moved to the device axis).  The UNGATED "
                     "dispatch overhead per rung is reported beside "
                     "it (dispatch_overhead_p50_us; the perfgate "
                     "flat-ish budget)."),
        },
    }
    print(json.dumps(result), flush=True)


def _bench_txn() -> None:
    """--txn mode: transaction throughput — single-group MULTI batches
    vs cross-group 2PC cost (PR 12), under the SAME per-group write
    service-capacity gate as the multi-group ladder (every rung pays
    APUS_WRITE_SVC_US per write at its group's leader; the 2PC rung
    additionally pays its prepare/commit records there, so the
    reported ratio IS the protocol's cost under the deployment model
    the gate emulates).  The group-major device plane runs throughout
    and the recompile sentinel must read zero — transaction records
    are ordinary log entries, no new dispatch shapes.

    Env knobs: APUS_TXN_CLIENTS (8), APUS_TXN_SECONDS (3.0),
    APUS_TXN_WSVC_MS (1.5)."""
    import dataclasses
    import threading

    from apus_tpu.runtime.client import ApusClient, probe_status
    from apus_tpu.runtime.cluster import LocalCluster
    from apus_tpu.runtime.router import group_of_key
    from apus_tpu.utils.config import ClusterSpec

    P = int(os.environ.get("APUS_TXN_CLIENTS", "8"))
    seconds = float(os.environ.get("APUS_TXN_SECONDS", "3.0"))
    R = 3
    wsvc_ms = float(os.environ.get("APUS_TXN_WSVC_MS", "1.5"))
    spec = ClusterSpec(hb_period=0.005, hb_timeout=0.030,
                       elect_low=0.050, elect_high=0.150, groups=2)
    os.environ["APUS_WRITE_SVC_US"] = str(int(wsvc_ms * 1000))
    k_of = {g: [k for k in (b"b%d" % i for i in range(64))
                if group_of_key(k, 2) == g][:16] for g in (0, 1)}
    rungs: dict[str, dict] = {}
    try:
        with LocalCluster(R, spec=spec, groups=2, device_plane=True,
                          device_batch=16, group_major=True) as c:
            c.wait_for_group_leaders(timeout=30.0)
            peers = list(c.spec.peers)
            from apus_tpu.runtime.device_plane import \
                unexpected_compiles
            for mode, label in (("multi", "single-group MULTI batch"),
                                ("2pc", "cross-group 2PC")):
                done = [0] * P
                fails = [0] * P
                stop_at = time.monotonic() + seconds

                def worker(w, mode=mode, stop_at=stop_at):
                    with ApusClient(peers, groups=2, timeout=30.0,
                                    attempt_timeout=10.0) as cl:
                        i = 0
                        while time.monotonic() < stop_at:
                            i += 1
                            g = (w + i) % 2
                            ks = k_of[g]
                            try:
                                if mode == "multi":
                                    # 4 writes, ONE group, one TM
                                    # entry.
                                    cl.txn([
                                        ("put", ks[(i + j) % len(ks)],
                                         b"v%d" % i)
                                        for j in range(4)])
                                    done[w] += 4
                                else:
                                    # 2 writes SPANNING groups: the
                                    # replicated 2PC.
                                    cl.txn([
                                        ("put",
                                         k_of[0][(w + i) % 16],
                                         b"v%d" % i),
                                        ("put",
                                         k_of[1][(w + i) % 16],
                                         b"v%d" % i)])
                                    done[w] += 2
                            except (TimeoutError, RuntimeError):
                                fails[w] += 1
                                if fails[w] > 5:
                                    return

                _mark(f"txn rung '{label}': {P} clients, "
                      f"{seconds:.1f}s, write-svc {wsvc_ms:.2f} "
                      f"ms/op/group")
                t0 = time.monotonic()
                threads = [threading.Thread(target=worker, args=(w,))
                           for w in range(P)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                elapsed = time.monotonic() - t0
                sweep = {f: 0 for f in ("txn_decided", "txn_batches",
                                        "txn_aborted",
                                        "txn_lock_conflicts")}
                for addr in peers:
                    st = probe_status(addr, timeout=2.0) or {}
                    for f in sweep:
                        sweep[f] += st.get(f, 0) or 0
                rungs[mode] = {
                    "label": label,
                    "write_subs_per_sec": round(sum(done) / elapsed,
                                                1),
                    "txns_per_sec": round(
                        sum(done) / (4 if mode == "multi" else 2)
                        / elapsed, 1),
                    "elapsed_s": round(elapsed, 3),
                    "client_failures": sum(fails),
                    "counters": sweep,
                }
                _mark(f"  {label}: "
                      f"{rungs[mode]['txns_per_sec']:.0f} txns/s "
                      f"({rungs[mode]['write_subs_per_sec']:.0f} "
                      f"write subs/s)")
            sentinel = unexpected_compiles()
    finally:
        os.environ.pop("APUS_WRITE_SVC_US", None)
    multi = rungs["multi"]["txns_per_sec"] or 1.0
    cross = rungs["2pc"]["txns_per_sec"]
    result = {
        "metric": f"txn_throughput_{P}c_{R}rep",
        "value": cross,
        "unit": "cross-group txns/s",
        "vs_baseline": round(cross / multi, 3),
        "detail": {
            "mode": "txn",
            "replicas": R, "clients": P,
            "seconds_per_rung": seconds,
            "emulated_write_svc_ms": wsvc_ms,
            "rungs": rungs,
            "single_group_txns_per_sec": multi,
            "cross_group_2pc_txns_per_sec": cross,
            "cost_ratio_2pc_vs_multi": round(multi / max(cross, 0.1),
                                             2),
            "recompile_sentinel": sentinel,
            "note": ("both rungs pay the identical per-group write "
                     "service gate; the 2PC rung's extra TP/TC "
                     "records pay it too, so the ratio reports the "
                     "protocol's real amplification under the "
                     "gate-emulated multi-core deployment"),
        },
    }
    print(json.dumps(result), flush=True)


def _bench_breakdown() -> None:
    """--breakdown mode: per-stage latency decomposition of the
    pipelined PUT path (the paper's per-stage evaluation axis, and the
    baseline the native-hot-path PR must beat stage by stage).

    Drives P pipelined clients against a live LocalCluster with the
    observability plane sampling aggressively (APUS_OBS_SAMPLE=16),
    then reads the answer two ways:

    - STITCHED (exact): the daemons' span rings + the clients' tracers
      live in this process, so every sampled op's stamps stitch into
      exact per-stage durations — the banked per-stage p50/p99 table,
      with wire_in/wire_out (client <-> server hops) included.
    - SCRAPED (wire path): OP_METRICS histograms from the leader — the
      log2-bucket per-stage p50s a production scrape would see,
      reported alongside for cross-validation.

    The cluster runs WITH the in-process device plane (ISSUE 8), so
    the table carries the device hops too: sampled ops that rode a
    device window gain ``dev_dispatch_wait`` (repl -> window handed to
    the jitted engine) and ``dev_execute`` (dispatch -> device quorum
    resolved) rows, and the scraped ``dev_*`` dispatch/occupancy
    histograms + the recompile-sentinel count land in the banked
    detail.

    Stage durations telescope (their per-op sum == server e2e), so the
    acceptance check "sum of stage p50s within 20% of end-to-end p50"
    is reported as ``stage_sum_vs_e2e``.  Env knobs: APUS_BRK_CLIENTS
    (4), APUS_BRK_SECONDS (3.0), APUS_BRK_REPLICAS (3),
    APUS_BRK_DEVPLANE (1; 0 reverts to the host-only cluster)."""
    import statistics
    import threading

    from apus_tpu.obs.service import fetch_metrics
    from apus_tpu.obs.spans import STAGE_DURATIONS, SpanRecorder
    from apus_tpu.runtime.client import ApusClient
    from apus_tpu.runtime.cluster import LocalCluster

    P = int(os.environ.get("APUS_BRK_CLIENTS", "4"))
    seconds = float(os.environ.get("APUS_BRK_SECONDS", "3.0"))
    R = int(os.environ.get("APUS_BRK_REPLICAS", "3"))
    devplane = os.environ.get("APUS_BRK_DEVPLANE", "1") != "0"
    os.environ.setdefault("APUS_OBS_SAMPLE", "16")
    sample = int(os.environ["APUS_OBS_SAMPLE"])

    tracers = [SpanRecorder(sample_period=sample, capacity=16384)
               for _ in range(P)]
    with LocalCluster(R, device_plane=devplane) as c:
        leader = c.wait_for_leader(30.0)
        peers = list(c.spec.peers)
        stop_at = time.monotonic() + seconds
        done = [0] * P

        def worker(w: int):
            with ApusClient(peers, timeout=30.0,
                            tracer=tracers[w]) as cl:
                i = 0
                while time.monotonic() < stop_at:
                    cl.pipeline_puts(
                        [(b"b%d-%d-%d" % (w, i, j), b"v" * 64)
                         for j in range(64)])
                    done[w] += 64
                    i += 1

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(P)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0

        # -- stitch: in-process rings, exact monotonic stamps ----------
        ops: dict[tuple, dict] = {}
        op_idx: dict[tuple, int] = {}
        dev_events: list[dict] = []
        sources = [d.obs.spans.events() for d in c.daemons
                   if d is not None and d.obs is not None]
        sources += [tr.events() for tr in tracers]
        for evs in sources:
            for ev in evs:
                if not ev.get("req"):
                    # Device window events ride the ring with req=0
                    # and an idx-range [idx, hi) — collected for the
                    # per-op device hops below.
                    if ev.get("hi") is not None \
                            and ev.get("stage", "").startswith("dev_"):
                        dev_events.append(ev)
                    continue
                key = (ev.get("clt", 0), ev["req"])
                ops.setdefault(key, {})[ev["stage"]] = \
                    min(ops.get(key, {}).get(ev["stage"], 1 << 62),
                        ev["t_us"])
                if ev.get("idx") is not None:
                    op_idx[key] = ev["idx"]
        scraped = fetch_metrics(peers[leader.idx], timeout=5.0) or {}

    # Attach the device window hops to the sampled ops they carried:
    # the first dev_dispatch/dev_ready event whose [idx, hi) covers
    # the op's log index stamps that stage (same clock — the runner,
    # drivers and clients share this process's monotonic clock).
    if dev_events:
        dev_events.sort(key=lambda e: e["t_us"])
        for key, idx in op_idx.items():
            stamps = ops.get(key)
            if stamps is None:
                continue
            for ev in dev_events:
                st = ev["stage"]
                if st not in stamps and ev["idx"] <= idx < ev["hi"]:
                    stamps[st] = ev["t_us"]

    order = ["client_send", "ingest", "lock", "admit", "append",
             "repl", "dev_dispatch", "dev_ready", "quorum", "apply",
             "fsync", "reply", "client_reply"]
    names = {"ingest": "wire_in",
             "dev_dispatch": "dev_dispatch_wait",
             "dev_ready": "dev_execute",
             **STAGE_DURATIONS}
    durs: dict[str, list] = {}
    modal_durs: dict[str, list] = {}
    e2e_server, e2e_client = [], []
    e2e_server_modal, e2e_client_modal = [], []
    shape_counts: dict[tuple, int] = {}
    kept: list = []
    for stamps in ops.values():
        # Only fully-telescoped chains keep the sum == e2e identity
        # (ring wrap can drop an op's early stamps): client bracket +
        # server bracket required.
        if not all(s in stamps for s in ("client_send", "ingest",
                                         "reply", "client_reply")):
            continue
        present = tuple(s for s in order if s in stamps)
        shape_counts[present] = shape_counts.get(present, 0) + 1
        kept.append((present, stamps))
    # The device plane splits the op population into chain SHAPES
    # (ops that rode a device window carry dev hops, host-path ops do
    # not); summing per-stage p50s across heterogeneous shapes breaks
    # the telescoping identity, so the acceptance ratio is computed
    # over the MODAL shape only — within one shape, durations
    # telescope per op and the p50 sum tracks the e2e p50 again.  The
    # stage table still aggregates every op.
    modal = max(shape_counts, key=shape_counts.get) \
        if shape_counts else ()
    for present, stamps in kept:
        is_modal = present == modal
        for a, b in zip(present, present[1:]):
            v = max(0, stamps[b] - stamps[a])
            durs.setdefault(names.get(b, b), []).append(v)
            if is_modal:
                modal_durs.setdefault(names.get(b, b), []).append(v)
        e2e_server.append(stamps["reply"] - stamps["ingest"])
        e2e_client.append(stamps["client_reply"]
                          - stamps["client_send"])
        if is_modal:
            e2e_server_modal.append(stamps["reply"] - stamps["ingest"])
            e2e_client_modal.append(stamps["client_reply"]
                                    - stamps["client_send"])

    def pcts(vals):
        if not vals:
            return None
        vs = sorted(vals)
        return {"p50": round(statistics.median(vs), 1),
                "p99": round(vs[min(len(vs) - 1,
                                    int(0.99 * len(vs)))], 1),
                "n": len(vs)}

    stages = {name: pcts(v) for name, v in durs.items() if v}
    m_stages = {name: pcts(v) for name, v in modal_durs.items() if v}
    # The acceptance chain: every named stage of the modal shape's
    # client-to-client telescope; their per-op durations sum exactly
    # to the client e2e, so the p50 sum tracks the e2e p50.
    chain_names = [names.get(s, s) for s in modal[1:]]
    chain_names = [n for n in chain_names if n in m_stages]
    srv_stage_names = [names.get(s, s) for s in modal
                       if s not in ("client_send", "client_reply",
                                    "ingest")]
    srv_stage_names = [n for n in srv_stage_names if n in m_stages]
    stage_p50_sum = sum(m_stages[n]["p50"] for n in chain_names)
    srv_p50_sum = sum(m_stages[n]["p50"] for n in srv_stage_names)
    e2e = pcts(e2e_client) or {"p50": 0.0}
    e2e_srv = pcts(e2e_server) or {"p50": 0.0}
    e2e_modal = pcts(e2e_client_modal) or {"p50": 0.0}
    e2e_srv_modal = pcts(e2e_server_modal) or {"p50": 0.0}
    ratio = stage_p50_sum / e2e_modal["p50"] if e2e_modal["p50"] \
        else 0.0

    met = scraped.get("metrics", {})
    scraped_stages = {
        k: {"p50": v.get("p50"), "p99": v.get("p99"),
            "n": v.get("count")}
        for k, v in met.items()
        if v.get("type") == "histogram" and v.get("count")}
    # Device-plane telemetry (merged into the leader's scrape by the
    # obs service): dispatch/occupancy distributions + the recompile
    # sentinel reading — the acceptance claim "sentinel reads zero
    # across the standard bench" is this banked field.
    dev_summary = {
        k: (v.get("value")
            if v.get("type") in ("counter", "gauge")
            else {"p50": v.get("p50"), "p99": v.get("p99"),
                  "n": v.get("count")})
        for k, v in met.items() if k.startswith(("dev_", "devd_"))}
    dev_recompiles = (met.get("dev_recompiles") or {}).get("value", 0)

    result = {
        "metric": "pipelined_put_stage_breakdown",
        "value": e2e["p50"],
        "unit": "us (client e2e p50)",
        "vs_baseline": round(ratio, 3),
        "detail": {
            "mode": "breakdown",
            "replicas": R, "clients": P, "window": 64,
            "sample_period": sample,
            "ops_per_sec": round(sum(done) / elapsed, 1),
            "sampled_ops_stitched": len(e2e_client),
            "stages_us": stages,
            "named_stages": chain_names,
            "named_server_stages": srv_stage_names,
            "stage_p50_sum_us": round(stage_p50_sum, 1),
            "server_stage_p50_sum_us": round(srv_p50_sum, 1),
            "e2e_client_us": e2e,
            "e2e_server_us": e2e_srv,
            "modal_chain": list(modal),
            "modal_chain_ops": shape_counts.get(modal, 0),
            "modal_e2e_client_us": e2e_modal,
            "stage_sum_vs_e2e": round(ratio, 3),
            "server_stage_sum_vs_server_e2e": round(
                srv_p50_sum / e2e_srv_modal["p50"], 3)
            if e2e_srv_modal["p50"] else 0.0,
            "scraped_histograms_us": scraped_stages,
            "device_plane": devplane,
            "device_windows_seen": sum(
                1 for e in dev_events if e["stage"] == "dev_dispatch"),
            "dev_recompiles": dev_recompiles,
            "device_metrics": dev_summary,
            "health": scraped.get("health"),
            "note": ("stages_us are exact stitched durations from the "
                     "in-process span rings (client+daemons share a "
                     "monotonic clock); scraped_histograms_us are the "
                     "log2-bucket OP_METRICS view of the same run. "
                     "Stage durations telescope, so stage_sum_vs_e2e "
                     "~ 1.0 by construction.  dev_dispatch_wait/"
                     "dev_execute rows exist for ops that rode a "
                     "device window; device_metrics is the merged "
                     "dev_* scrape (recompile sentinel included)."),
        },
    }
    print(json.dumps(result), flush=True)


def _bench_perkey() -> None:
    """--perkey mode (ISSUE 15): per-bucket follower-lease
    invalidation vs the whole-log baseline, measured where it matters —
    follower-lease GET throughput on COLD keys while a concurrent
    hot-key writer hammers ONE key in a different bucket.

    Under whole-log gating every cold read at a follower waits for
    apply to cover the follower's whole log end at registration (the
    hot write stream drags that forward continuously) and every hot
    commit waits on every lease holder's ack; under bucket-granular
    leases the cold buckets decouple (grant floors and wait rules are
    per bucket, commit bypasses disjoint-set holders —
    node_flr_commit_bypass counts the relief).  Same per-replica read
    service gate both rows (APUS_PK_READ_SVC_US -> APUS_READ_SVC_US,
    the PR 9 methodology: each replica owns one core).

    Env knobs: APUS_PK_SECONDS (3.0), APUS_PK_READERS (4),
    APUS_PK_WRITERS (2), APUS_PK_READ_SVC_US (200), APUS_PK_WINDOW
    (32).  Headline: value = bucketed cold-GET ops/s; vs_baseline =
    bucketed/whole-log ratio (acceptance >= 2.0)."""
    import dataclasses
    import threading

    from apus_tpu.runtime.client import ApusClient, probe_status
    from apus_tpu.runtime.cluster import LocalCluster
    from apus_tpu.runtime.router import bucket_of_key
    from apus_tpu.utils.config import ClusterSpec

    seconds = float(os.environ.get("APUS_PK_SECONDS", "3.0"))
    readers = int(os.environ.get("APUS_PK_READERS", "2"))
    writers = int(os.environ.get("APUS_PK_WRITERS", "1"))
    svc_us = os.environ.get("APUS_PK_READ_SVC_US", "50")
    W = int(os.environ.get("APUS_PK_WINDOW", "8"))
    #: hot writer in-flight window: the depth of the uncommitted hot
    #: tail a whole-log-gated cold read can find itself parked behind
    #: — the "heavy write pressure" knob of the scenario.
    WW = int(os.environ.get("APUS_PK_WRITE_WINDOW", "256"))
    #: hot value size: follower APPLY cost per hot entry — the load a
    #: whole-log-gated cold read waits behind.
    hv = b"H" * int(os.environ.get("APUS_PK_VALUE", "2048"))
    #: emulated replication-link latency (leader -> followers), ms.
    repl_ms = float(os.environ.get("APUS_PK_REPL_MS", "4.0"))
    # The PROXIED timing envelope (hb 10 ms / timeout 100 ms): python
    # daemons GIL-starved by the hot writer + the emulated link delay
    # flap the leader LEASE at tighter envelopes, which would measure
    # lease churn, not the gating rule under test.
    spec0 = ClusterSpec(hb_period=0.010, hb_timeout=0.100,
                        elect_low=0.150, elect_high=0.400)

    hot = b"hot-key"
    hot_b = bucket_of_key(hot)
    cold: list[bytes] = []
    i = 0
    while len(cold) < readers * W:
        k = b"cold-%05d" % i
        i += 1
        if bucket_of_key(k) != hot_b:
            cold.append(k)

    def run(bucketed: bool) -> dict:
        os.environ["APUS_READ_SVC_US"] = svc_us
        try:
            spec = dataclasses.replace(spec0, fault_plane=True,
                                       flr_bucket_leases=bucketed)
            with LocalCluster(3, spec=spec) as c:
                lead = c.wait_for_leader(30.0)
                peers = list(c.spec.peers)
                if repl_ms > 0:
                    # Emulated replication-link latency (cross-AZ
                    # deployment shape), leader -> both followers,
                    # IDENTICAL in both rows: entries and commit
                    # offsets reach followers one link delay late, so
                    # a whole-log-gated cold read really does park
                    # behind the hot stream's in-flight tail — the
                    # coupling this bench measures.
                    for f in range(3):
                        if f != lead.idx:
                            lead.transport.set_delay(f, repl_ms / 1e3)
                with ApusClient(peers, timeout=20.0) as warm:
                    warm.put(hot, b"h0")
                    for lo in range(0, len(cold), 16):
                        warm.pipeline_puts(
                            [(k, b"c" * 64)
                             for k in cold[lo:lo + 16]])
                stop_at = time.monotonic() + seconds
                reads_done = [0] * readers
                writes_done = [0] * writers

                def write_worker(w):
                    from apus_tpu.models.kvs import encode_put
                    from apus_tpu.runtime.client import OP_CLT_WRITE
                    with ApusClient(peers, timeout=30.0) as cl:
                        j = 0
                        while time.monotonic() < stop_at:
                            try:
                                cl.pipeline(
                                    [(OP_CLT_WRITE,
                                      encode_put(hot, hv + b"%d-%d"
                                                 % (w, j + k)))
                                     for k in range(WW)], window=WW)
                                writes_done[w] += WW
                                j += WW
                            except (TimeoutError, RuntimeError):
                                return

                def read_worker(r):
                    keys = cold[r * W:(r + 1) * W]
                    with ApusClient(peers, timeout=30.0,
                                    read_policy="spread") as cl:
                        while time.monotonic() < stop_at:
                            try:
                                cl.pipeline_gets(keys)
                                reads_done[r] += len(keys)
                            except (TimeoutError, RuntimeError):
                                return

                ts = [threading.Thread(target=write_worker, args=(w,))
                      for w in range(writers)]
                ts += [threading.Thread(target=read_worker, args=(r,))
                       for r in range(readers)]
                t0 = time.monotonic()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=seconds + 30.0)
                elapsed = time.monotonic() - t0
                lead_st = probe_status(peers[lead.idx],
                                       timeout=2.0) or {}
                flr_reads = 0
                for p in peers:
                    st = probe_status(p, timeout=2.0) or {}
                    flr_reads += st.get("flr_local_reads", 0) or 0
                return {
                    "cold_get_ops_per_sec": round(
                        sum(reads_done) / elapsed, 1),
                    "cold_gets": sum(reads_done),
                    "hot_writes": sum(writes_done),
                    "hot_write_ops_per_sec": round(
                        sum(writes_done) / elapsed, 1),
                    "elapsed_s": round(elapsed, 3),
                    "flr_local_reads": flr_reads,
                    "flr_commit_bypass": lead_st.get(
                        "flr_commit_bypass", 0),
                    "flr_commit_blocked": lead_st.get(
                        "flr_commit_blocked", 0),
                    "flr_bucket_grants": lead_st.get(
                        "flr_bucket_grants", 0),
                }
        finally:
            os.environ.pop("APUS_READ_SVC_US", None)

    _mark("perkey: bucket-granular row")
    row_bucket = run(bucketed=True)
    _mark("perkey: whole-log baseline row")
    row_whole = run(bucketed=False)
    ratio = (row_bucket["cold_get_ops_per_sec"]
             / max(1e-9, row_whole["cold_get_ops_per_sec"]))
    result = {
        "metric": "perkey_invalidation_cold_get_gain",
        "value": row_bucket["cold_get_ops_per_sec"],
        "unit": "cold-key follower GET ops/s (bucket-granular row)",
        "vs_baseline": round(ratio, 2),
        "detail": {
            "mode": "perkey",
            "acceptance": "bucketed/whole-log >= 2.0 (ISSUE 15)",
            "read_svc_us_both_rows": float(svc_us),
            "readers": readers, "writers": writers, "window": W,
            "hot_bucket": hot_b,
            "bucket_granular": row_bucket,
            "whole_log_baseline": row_whole,
            "note": ("one hot-key pipelined writer stream vs "
                     "cold-bucket spread GETs; same clusters, same "
                     "per-replica read service gate, only "
                     "flr_bucket_leases differs.  flr_commit_bypass "
                     "counts commits the whole-log rule would have "
                     "held for a lease holder's ack."),
        },
    }
    print(json.dumps(result), flush=True)


def _bench_slo() -> None:
    """--slo mode (ISSUE 15): the open-loop SLO harness headline.

    Phase 1 (clean): >=512 open-loop connections at a fixed arrival
    rate against a live 3-replica ProcCluster — zipfian hot-key skew,
    seeded connection churn, periodic fan-in bursts — p50/p99/p999
    measured coordinated-omission-safe (latency anchored at scheduled
    arrivals; apus_tpu/load).  Phase 2 (chaos-composed): same load
    with the LEADER SIGKILLED mid-run and restarted — the report's
    windowed view quantifies the SLO degradation window around the
    failover.

    Env knobs: APUS_SLO_CONNS (512), APUS_SLO_RATE (1200 ops/s),
    APUS_SLO_SECONDS (10), APUS_SLO_MS (100 — the per-window p99 SLO
    threshold), APUS_SLO_VALUE (64), APUS_SLO_KEYS (20000)."""
    import tempfile
    import threading

    from apus_tpu.load import OpenLoopConfig, run_open_loop
    from apus_tpu.obs.service import fetch_metrics
    from apus_tpu.runtime.proc import ProcCluster

    conns = int(os.environ.get("APUS_SLO_CONNS", "512"))
    rate = float(os.environ.get("APUS_SLO_RATE", "800"))
    seconds = float(os.environ.get("APUS_SLO_SECONDS", "10"))
    slo_ms = float(os.environ.get("APUS_SLO_MS", "400"))
    value = int(os.environ.get("APUS_SLO_VALUE", "64"))
    nkeys = int(os.environ.get("APUS_SLO_KEYS", "20000"))

    def cfg(peers, seed):
        return OpenLoopConfig(
            peers=peers, connections=conns, rate=rate,
            duration=seconds, seed=seed, nkeys=nkeys, theta=0.99,
            get_fraction=0.9, value_size=value, churn_every=2.0,
            churn_fraction=0.05, burst_every=2.5,
            burst_size=max(32, conns // 8), slo_ms=slo_ms,
            window_s=0.5, grace=20.0)

    def slim(rep):
        d = rep.to_dict()
        d["windows"] = [(round(t, 2), n, round(p, 2), bad, sheds)
                        for t, n, p, bad, sheds in d["windows"]]
        return d

    with tempfile.TemporaryDirectory(prefix="apus-slo") as td:
        with ProcCluster(3, workdir=td) as pc:
            pc.leader_idx(timeout=30.0)
            peers = [p for p in pc.spec.peers if p]
            _mark(f"slo: clean open-loop run ({conns} conns @ "
                  f"{rate:.0f}/s x {seconds:.0f}s)")
            clean_rep, clean_stats = run_open_loop(cfg(peers, seed=15))

            _mark("slo: chaos-composed run (leader kill mid-load)")
            kill_log: dict = {}

            def nemesis():
                time.sleep(seconds * 0.4)
                try:
                    lead = pc.leader_idx(timeout=5.0)
                except AssertionError:
                    return
                kill_log["killed"] = lead
                kill_log["t_kill_s"] = round(seconds * 0.4, 2)
                pc.kill(lead)
                time.sleep(2.0)
                try:
                    pc.restart(lead)
                    kill_log["restarted"] = True
                except AssertionError:
                    kill_log["restarted"] = False

            nt = threading.Thread(target=nemesis, daemon=True)
            nt.start()
            chaos_rep, chaos_stats = run_open_loop(cfg(peers, seed=16))
            nt.join(timeout=30.0)

            health = []
            for p in peers:
                m = fetch_metrics(p, timeout=2.0) or {}
                met = m.get("metrics", {}) or {}
                rc = met.get("dev_recompiles", 0)
                if isinstance(rc, dict):
                    rc = rc.get("value", 0)
                health.append({
                    "replica": m.get("replica"),
                    "dev_recompiles": rc,
                    "flags": (m.get("health") or {}).get("flags", []),
                })

    clean = slim(clean_rep)
    chaos = slim(chaos_rep)
    result = {
        "metric": "open_loop_slo_get_set_p99",
        "value": clean["p99_ms"],
        "unit": "ms (clean-run p99, CO-safe, scheduled-arrival "
                "anchored)",
        "vs_baseline": round(clean["achieved_rate"] / rate, 3),
        "detail": {
            "mode": "slo",
            "connections": conns, "rate_ops_s": rate,
            "duration_s": seconds, "slo_ms": slo_ms,
            "zipf_theta": 0.99, "nkeys": nkeys,
            "get_fraction": 0.9,
            "clean": {"report": clean, "stats": clean_stats},
            "chaos": {"report": chaos, "stats": chaos_stats,
                      "nemesis": kill_log,
                      "degraded_s": chaos["degraded_s"],
                      "degraded_spans": chaos["degraded_spans"]},
            "recompile_sentinel": [h["dev_recompiles"] for h in health],
            "health": health,
            "note": ("open-loop: arrivals pre-scheduled at the target "
                     "rate, never slowed by the server; latency = "
                     "completion - scheduled arrival (coordinated-"
                     "omission-safe), unresolved ops censored into "
                     "the tail.  Chaos run composes seeded connection "
                     "churn + fan-in bursts with a mid-run leader "
                     "SIGKILL + restart; degraded_spans quantifies "
                     "the SLO outage window."),
        },
    }
    print(json.dumps(result), flush=True)


def _bench_overload() -> None:
    """--overload mode (ISSUE 17): the overload-control headline.

    Three phases against one live 3-replica ProcCluster with SHRUNK
    admission budgets (so saturation is reachable in seconds on this
    1-core box — the gating RULES under test are size-independent):

    1. saturation ramp: staircase the offered rate and locate the
       goodput knee; past the knee the servers must REFUSE load with
       typed sheds, never ambiguous timeouts (0 censored);
    2. metastability probe: step to ~5x the knee and back — goodput
       under overload must hold >= ~70% of the knee (no congestion
       collapse) and the tail must settle within a bounded window
       after the step-down (no metastable wake);
    3. chaos: the same flood composed with a mid-run leader SIGKILL +
       restart — the degraded window is compared against the clean
       serving baseline (PR 15 banked 5.5 s for the un-floodeed kill).

    Env knobs: APUS_OVL_CONNS (64), APUS_OVL_START/STEP (300/300
    ops/s), APUS_OVL_STEPS (6), APUS_OVL_STEP_S (4), APUS_OVL_X (5),
    plus the admission budgets APUS_OVL_MAX_INFLIGHT (64) /
    APUS_OVL_MAX_PER_CONN (16) / APUS_OVL_RETRY_MS (25) exported to
    the daemons before spawn."""
    import dataclasses
    import tempfile
    import threading

    from apus_tpu.load import (OpenLoopConfig, run_metastability,
                               run_open_loop, run_saturation_ramp)
    from apus_tpu.runtime.proc import ProcCluster
    from apus_tpu.utils.config import ClusterSpec

    conns = int(os.environ.get("APUS_OVL_CONNS", "64"))
    start = float(os.environ.get("APUS_OVL_START", "300"))
    step = float(os.environ.get("APUS_OVL_STEP", "300"))
    steps = int(os.environ.get("APUS_OVL_STEPS", "6"))
    step_s = float(os.environ.get("APUS_OVL_STEP_S", "4"))
    over_x = float(os.environ.get("APUS_OVL_X", "5"))
    # Shrunk admission budgets (children inherit os.environ).
    os.environ.setdefault("APUS_OVL_MAX_INFLIGHT", "64")
    os.environ.setdefault("APUS_OVL_MAX_PER_CONN", "16")
    os.environ.setdefault("APUS_OVL_RETRY_MS", "25")
    budgets = {k: os.environ[k] for k in
               ("APUS_OVL_MAX_INFLIGHT", "APUS_OVL_MAX_PER_CONN",
                "APUS_OVL_RETRY_MS")}

    # PROXIED envelope (same rationale as --perkey / overload_smoke):
    # GIL-starved daemons flap leaders at PROC_SPEC's 10 ms election
    # timeout under a flood, which would measure timer tightness, not
    # the admission gates.
    spec = ClusterSpec(hb_period=0.010, hb_timeout=0.100,
                       elect_low=0.150, elect_high=0.400)

    def cfg(peers, seed, rate):
        return OpenLoopConfig(
            peers=peers, connections=conns, rate=rate, duration=step_s,
            seed=seed, nkeys=4096, theta=0.0, get_fraction=0.5,
            value_size=64, slo_ms=200.0, window_s=0.5, grace=10.0)

    def slim(d):
        d = dict(d)
        d["windows"] = [(round(t, 2), n, round(p, 2), bad, sheds)
                        for t, n, p, bad, sheds in d["windows"]]
        return d

    with tempfile.TemporaryDirectory(prefix="apus-ovl") as td:
        with ProcCluster(3, workdir=td, spec=spec) as pc:
            pc.leader_idx(timeout=30.0)
            peers = [p for p in pc.spec.peers if p]

            _mark(f"overload: saturation ramp ({start:.0f}/s + "
                  f"{steps}x{step:.0f}/s, {step_s:.0f}s steps)")
            ramp = run_saturation_ramp(
                cfg(peers, seed=1701, rate=start), start, step, steps,
                step_s, log=_mark)

            base = max(start, ramp["knee_rate"] * 0.5)
            _mark(f"overload: metastability probe (base {base:.0f}/s "
                  f"-> x{over_x:g} -> back)")
            meta = run_metastability(
                cfg(peers, seed=1777, rate=base), overload_x=over_x,
                base_s=4.0, overload_s=4.0, recover_s=8.0, log=_mark)
            meta_slim = dict(meta)
            meta_slim["report"] = slim(meta["report"])

            _mark("overload: chaos run (busy load + leader kill "
                  "mid-run)")
            # Sustainable-but-busy (half the knee) at the SAME window
            # SLO the PR 15 serving baseline used (400 ms): the
            # degraded window then ISOLATES the kill and is directly
            # comparable to that banked 5.5 s; past-knee behavior is
            # the metastability probe's job.
            chaos_rate = ramp["knee_goodput"] * 0.5
            chaos_s = 12.0
            kill_log: dict = {}

            def nemesis():
                time.sleep(chaos_s * 0.4)
                try:
                    lead = pc.leader_idx(timeout=5.0)
                except AssertionError:
                    return
                kill_log["killed"] = lead
                kill_log["t_kill_s"] = round(chaos_s * 0.4, 2)
                pc.kill(lead)
                time.sleep(2.0)
                try:
                    pc.restart(lead)
                    kill_log["restarted"] = True
                except AssertionError:
                    kill_log["restarted"] = False

            ccfg = cfg(peers, seed=1801, rate=chaos_rate)
            ccfg = dataclasses.replace(ccfg, duration=chaos_s,
                                       grace=20.0, slo_ms=400.0)
            nt = threading.Thread(target=nemesis, daemon=True)
            nt.start()
            chaos_rep, chaos_stats = run_open_loop(ccfg)
            nt.join(timeout=30.0)

            srv = {"admitted": 0, "shed_total": 0}
            for i in range(3):
                st = pc.status(i, timeout=1.0) or {}
                ov = st.get("overload") or {}
                srv["admitted"] += ov.get("admitted", 0) or 0
                srv["shed_total"] += ov.get("shed_total", 0) or 0

    chaos = slim(chaos_rep.to_dict())
    good5x = next(p["goodput_rate"] for p in meta["phases"]
                  if p["phase"] == "overload")
    result = {
        "metric": "overload_knee_goodput",
        "value": round(ramp["knee_goodput"], 1),
        "unit": "ops/s (peak goodput at the saturation knee, "
                "CO-safe)",
        "vs_baseline": round(good5x / max(ramp["knee_goodput"], 1e-9),
                             3),
        "detail": {
            "mode": "overload", "connections": conns,
            "admission_budgets": budgets,
            "ramp": ramp,
            "goodput_under_overload_x": round(good5x, 1),
            "meta": meta_slim,
            "chaos": {"rate_ops_s": chaos_rate, "report": chaos,
                      "stats": chaos_stats, "nemesis": kill_log,
                      "degraded_s": chaos["degraded_s"],
                      "degraded_spans": chaos["degraded_spans"],
                      "pr15_clean_kill_window_s": 5.5},
            "server_overload": srv,
            "note": ("vs_baseline = goodput under the ~5x overload "
                     "step relative to the knee (>= ~0.7 means no "
                     "congestion collapse).  Sheds are typed "
                     "ST_OVERLOAD refusals counted OUTSIDE the "
                     "latency percentiles; censored==0 everywhere "
                     "means no op ever died an ambiguous timeout."),
        },
    }
    print(json.dumps(result), flush=True)


def _run_child(extra_env: dict, timeout_s: float) -> dict | None:
    """Run the measurement in a watched subprocess; return the parsed
    JSON result or None on failure/timeout (stderr passes through)."""
    env = dict(os.environ)
    env.update(extra_env)
    env["_APUS_BENCH_CHILD"] = "1"
    env["_APUS_BENCH_DEADLINE"] = str(time.time() + timeout_s)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=sys.stderr,
            timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        print(f"bench: attempt timed out after {timeout_s:.0f}s "
              f"(env={extra_env})", file=sys.stderr)
        # The child flushes a complete headline JSON after every ladder
        # depth — a timeout may still have a valid result in its stdout.
        return _parse_last_json(e.stdout)
    except Exception as e:                       # noqa: BLE001 — must not die
        print(f"bench: attempt failed to launch: {e}", file=sys.stderr)
        return None
    if proc.returncode != 0:
        print(f"bench: attempt rc={proc.returncode} (env={extra_env})",
              file=sys.stderr)
        # A crash in an optional post-headline phase must not discard an
        # already-flushed headline JSON (mirrors the timeout salvage).
        return _parse_last_json(proc.stdout)
    result = _parse_last_json(proc.stdout)
    if result is None:
        print("bench: attempt produced no JSON line", file=sys.stderr)
    return result


def _parse_last_json(stdout: bytes | None) -> dict | None:
    if not stdout:
        return None
    for line in reversed(stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


_LAST_TPU = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_TPU_LAST.json")


def _code_fingerprint() -> str:
    """Content hash of the measurement-relevant sources (this file and
    the device data plane).  Robust where a git SHA is not: unrelated
    commits don't invalidate recorded evidence, and uncommitted edits
    to the measured code DO."""
    import hashlib
    root = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for rel in ("bench.py", "apus_tpu/ops/commit.py",
                "apus_tpu/ops/logplane.py", "apus_tpu/ops/mesh.py",
                "apus_tpu/ops/pallas_ring.py",
                "apus_tpu/runtime/device_plane.py"):
        p = os.path.join(root, rel)
        try:
            with open(p, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"<missing:%s>" % rel.encode())
    return h.hexdigest()[:16]


def _tpu_probe(timeout_s: float) -> bool:
    """Cheap tunnel-health probe: a trivial jit + scalar readback on the
    default (axon) backend.  A wedged tunnel hangs here in ~the same way
    it would hang the real attempt — failing fast (15 s) instead of
    burning a whole 60 s attempt window, so the parent can keep
    re-probing for a healthy window within its budget (wedges clear on
    their own; a retry often lands in the fast state)."""
    code = ("import jax; "
            "print(int(jax.jit(lambda x: x + 1)(jax.numpy.int32(41)))); ")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.DEVNULL, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print("bench: tpu probe timed out", file=sys.stderr)
        return False
    except Exception:                            # noqa: BLE001
        return False
    ok = proc.returncode == 0 and b"42" in proc.stdout
    if not ok:
        print(f"bench: tpu probe failed rc={proc.returncode}",
              file=sys.stderr)
    return ok


def main() -> None:
    if "--breakdown" in sys.argv[1:]:
        # Per-stage latency decomposition (host path, no JAX).
        try:
            _bench_breakdown()
        except Exception as e:                   # noqa: BLE001
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "metric": "pipelined_put_stage_breakdown",
                "value": None, "unit": "us (server e2e p50)",
                "vs_baseline": 0.0,
                "detail": {"mode": "breakdown", "error": repr(e)},
            }), flush=True)
        return
    if "--perkey" in sys.argv[1:]:
        # Per-bucket follower-lease invalidation A/B (ISSUE 15).
        try:
            _bench_perkey()
        except Exception as e:                   # noqa: BLE001
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "metric": "perkey_invalidation_cold_get_gain",
                "value": None, "unit": "cold-key follower GET ops/s",
                "vs_baseline": 0.0,
                "detail": {"mode": "perkey", "error": repr(e)},
            }), flush=True)
        return
    if "--slo" in sys.argv[1:]:
        # Open-loop SLO serving harness (ISSUE 15).
        try:
            _bench_slo()
        except Exception as e:                   # noqa: BLE001
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "metric": "open_loop_slo_get_set_p99",
                "value": None, "unit": "ms", "vs_baseline": 0.0,
                "detail": {"mode": "slo", "error": repr(e)},
            }), flush=True)
        return
    if "--overload" in sys.argv[1:]:
        # Overload control plane campaign (ISSUE 17): saturation ramp
        # to the goodput knee, ~5x metastability probe, and the flood
        # composed with a mid-run leader kill.
        try:
            _bench_overload()
        except Exception as e:                   # noqa: BLE001
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "metric": "overload_knee_goodput",
                "value": None, "unit": "ops/s", "vs_baseline": 0.0,
                "detail": {"mode": "overload", "error": repr(e)},
            }), flush=True)
        return
    if "--txn" in sys.argv[1:]:
        # Transaction throughput (PR 12): single-group MULTI batch vs
        # cross-group 2PC under the per-group write-svc gate, with the
        # group-major device plane on (recompile sentinel banked).
        try:
            _bench_txn()
        except Exception as e:                   # noqa: BLE001
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "metric": "txn_throughput",
                "value": None, "unit": "cross-group txns/s",
                "vs_baseline": 0.0,
                "detail": {"mode": "txn", "error": repr(e)},
            }), flush=True)
        return
    if "--devices" in sys.argv[1:]:
        # Multi-device group-window throughput ladder (ISSUE 14): the
        # group-major engine on a real (group, replica) device mesh,
        # async dispatch beat, per-device window service gate.  Must
        # run BEFORE anything imports jax (the rung device count rides
        # --xla_force_host_platform_device_count).
        argv = sys.argv[1:]
        try:
            devices_arg = argv[argv.index("--devices") + 1]
        except IndexError:
            devices_arg = "1,2,4"
        try:
            _bench_devices([int(d) for d in str(devices_arg).split(",")])
        except Exception as e:                   # noqa: BLE001
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "metric": "multidevice_group_window_throughput",
                "value": None, "unit": "group-windows/s",
                "vs_baseline": 0.0,
                "detail": {"mode": "devices", "error": repr(e)},
            }), flush=True)
        return
    if "--throughput" in sys.argv[1:]:
        # Host-path replicated throughput: runs inline (no JAX, no
        # TPU probe/watchdog scaffolding — live sockets on this host).
        # --groups N (or "1,2,4"): the multi-group sharded-consensus
        # ladder instead (group-major device plane ON — this mode DOES
        # import jax for the group-major dispatch counters).
        groups_arg = None
        argv = sys.argv[1:]
        if "--groups" in argv:
            try:
                groups_arg = argv[argv.index("--groups") + 1]
            except IndexError:
                groups_arg = "1,2,4"
        if groups_arg is not None:
            try:
                _bench_throughput_groups(
                    [int(g) for g in str(groups_arg).split(",")])
            except Exception as e:               # noqa: BLE001
                import traceback
                traceback.print_exc()
                print(json.dumps({
                    "metric": "multigroup_set_throughput",
                    "value": None, "unit": "ops/s", "vs_baseline": 0.0,
                    "detail": {"mode": "throughput_groups",
                               "error": repr(e)},
                }), flush=True)
            return
        try:
            _bench_throughput()
        except Exception as e:                   # noqa: BLE001
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "metric": "pipelined_set_throughput",
                "value": None, "unit": "ops/s", "vs_baseline": 0.0,
                "detail": {"mode": "throughput", "error": repr(e)},
            }), flush=True)
        return
    single_window = "--single-window" in sys.argv[1:] \
        or os.environ.get("_APUS_BENCH_MODE") == "single_window"
    if single_window:
        # Children re-exec this file without argv; the mode rides env.
        os.environ["_APUS_BENCH_MODE"] = "single_window"
    if os.environ.get("_APUS_BENCH_CHILD"):
        (_bench_single_window if single_window else _bench)()
        return

    t_start = time.monotonic()
    budget = float(os.environ.get("APUS_BENCH_BUDGET", "225"))
    tpu_timeout = float(os.environ.get("APUS_BENCH_TPU_TIMEOUT", "60"))

    result = None
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        # Probe-guarded TPU attempts: probe the tunnel cheaply (15 s)
        # and only spend a full attempt window on a healthy probe —
        # wedges last minutes and clear on their own, so keep probing
        # for a healthy window while the budget allows, reserving 45 s
        # for the CPU fallback.
        probe_deadline = t_start + budget - 45
        while time.monotonic() < probe_deadline:
            if not _tpu_probe(15):
                time.sleep(4)
                continue
            remaining = budget - (time.monotonic() - t_start) - 45
            if remaining < 20:
                break
            result = _run_child({}, min(tpu_timeout, remaining))
            if result is not None:
                break

    # Mode-keyed evidence file: a single-window TPU record must not
    # masquerade as the pipelined-ladder headline (different metric).
    last_tpu = _LAST_TPU.replace(".json", "_SW.json") if single_window \
        else _LAST_TPU

    if result is not None and result.get("detail", {}).get("backend") \
            not in (None, "cpu", "none"):
        # Record the successful TPU measurement for future fallbacks.
        try:
            with open(last_tpu, "w") as f:
                json.dump({"recorded_at_unix": int(time.time()),
                           "code_fingerprint": _code_fingerprint(),
                           "result": result}, f, indent=1)
        except OSError:
            pass

    if result is None:
        # CPU fallback: forced CPU backend (the depth ladder is
        # backend-keyed in the child).
        remaining = budget - (time.monotonic() - t_start)
        if remaining >= 20:
            result = _run_child(
                {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
                remaining)

    if result is None:
        # Degraded but well-formed: never leave the driver with rc!=0.
        result = {
            "metric": "single_window_commit_p50_latency_batch64_5rep"
                      if single_window else
                      "commit_round_p50_latency_batch64_5rep_pipelined",
            "value": None,
            "unit": "us",
            "vs_baseline": 0.0,
            "detail": {"backend": "none",
                       "error": "all backend attempts failed or timed out",
                       "baseline_round_us": BASELINE_ROUND_US},
        }
    if result.get("detail", {}).get("backend") in ("cpu", "none") \
            and os.path.exists(last_tpu):
        # Supplementary evidence only (clearly timestamped): the fresh
        # headline above remains the CPU measurement — this shows what
        # the same program measured on the real chip when the tunnel
        # was last healthy.
        try:
            with open(last_tpu) as f:
                prior = json.load(f)
            if prior.get("code_fingerprint") == _code_fingerprint():
                result["detail"]["prior_tpu_run"] = prior
        except (OSError, json.JSONDecodeError):
            pass
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
