"""Consensus-commit benchmark.  Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures the p50 latency of the jitted commit step — scatter of a
64-entry batch to a 5-replica group, fence check, quorum reduction,
commit advance — end to end from the host (dispatch + device execution),
which is the honest analog of the reference's commit path: leader RDMA
write fan-out + ack spin-poll (rc_write_remote_logs,
dare_ibv_rc.c:1870-1948).

Baseline: the reference repository publishes no numbers (BASELINE.md).
We baseline against the DARE/APUS RDMA envelope of ~15 us per commit
round on FDR InfiniBand (the order of magnitude the papers and the
repo's production timing constants imply: hb=1 ms, elect=10-30 ms,
nodes.local.cfg) — for a 64-entry batched round, per-entry cost
15/64 ≈ 0.23 us.  vs_baseline = baseline_p50 / our_p50 (>1 is better
than baseline).

Run on the real TPU chip (replicas folded onto one device: XLA executes
the identical collective program; ICI hops are absent, matching how the
driver benches single-chip).  Falls back to CPU when no TPU is present.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from apus_tpu.core.cid import Cid
    from apus_tpu.ops.commit import (CommitControl, build_commit_step,
                                     place_batch)
    from apus_tpu.ops.logplane import host_batch_to_device, make_device_log
    from apus_tpu.ops.mesh import replica_mesh, replica_sharding

    R, S, SB, B = 5, 4096, 4096, 64      # 5 replicas, 16 MB log each, 64-batch
    mesh = replica_mesh(R, devices=jax.devices()[:1])
    sh = replica_sharding(mesh)
    devlog = make_device_log(R, S, SB, batch=B, leader=0, term=1, sharding=sh)
    step = build_commit_step(mesh, R, S, SB, B, auto_advance=True)
    cid = Cid.initial(R)

    # Redis-SET-shaped payloads (the run.sh benchmark shape: redis-benchmark
    # -t set, benchmarks/run.sh:70-80).
    reqs = [b"*3\r\n$3\r\nSET\r\n$16\r\nkey:%012d\r\n$64\r\n%s\r\n"
            % (i, b"x" * 64) for i in range(B)]
    bd, bm, nv = host_batch_to_device(reqs, SB, batch_size=B)
    bdata, bmeta = place_batch(mesh, R, 0, bd, bm)

    end0 = 1
    ctrl = CommitControl.from_cid(cid, R, 0, 1, end0)

    # Warmup / compile.
    cur, _, commit, ctrl = step(devlog, bdata, bmeta, ctrl)
    jax.block_until_ready(commit)
    assert int(commit) == end0 + B, "bench step did not commit"

    iters = 200
    lat_us = []
    for i in range(iters):
        t0 = time.perf_counter_ns()
        cur, acks, commit, ctrl = step(cur, bdata, bmeta, ctrl)
        jax.block_until_ready(commit)
        lat_us.append((time.perf_counter_ns() - t0) / 1e3)
    lat_us.sort()
    p50 = lat_us[len(lat_us) // 2]
    p99 = lat_us[min(len(lat_us) - 1, int(len(lat_us) * 0.99))]
    per_entry_p50 = p50 / B
    commits_per_sec = B / (p50 / 1e6)

    baseline_round_us = 15.0             # RDMA commit-round envelope (see doc)
    vs_baseline = baseline_round_us / p50

    result = {
        "metric": "commit_step_p50_latency_batch64_5rep",
        "value": round(p50, 2),
        "unit": "us",
        "vs_baseline": round(vs_baseline, 4),
        "detail": {
            "backend": jax.default_backend(),
            "p99_us": round(p99, 2),
            "per_entry_p50_us": round(per_entry_p50, 4),
            "commits_per_sec": round(commits_per_sec),
            "batch": B, "replicas": R, "slot_bytes": SB,
            "baseline_round_us": baseline_round_us,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
