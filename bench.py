"""Consensus-commit benchmark.  Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures the per-round commit latency of the device-resident PIPELINED
commit path: ``depth`` consecutive commit rounds — each a full
leader->replicas scatter of a 64-entry batch, fence check, quorum
reduction, commit advance — execute inside one XLA program
(ops.commit.build_pipelined_commit_step), so the host dispatch cost is
amortized across rounds.  This mirrors how the reference reaches its
own numbers: its RDMA commit loop keeps many unsignaled WRs outstanding
and overlaps rounds in the NIC queue (post_send selective signaling,
dare_ibv_rc.c:2552-2568); ours keeps the round loop in HBM/MXU-land.
The single-dispatch (unpipelined) p50 is reported in ``detail`` — on a
tunneled TPU it is dominated by host<->device RTT.

Baseline: the reference repository publishes no numbers (BASELINE.md).
We baseline against the DARE/APUS RDMA envelope of ~15 us per commit
round on FDR InfiniBand (the order of magnitude the papers and the
repo's production timing constants imply: hb=1 ms, elect=10-30 ms,
nodes.local.cfg) — for a 64-entry batched round, per-entry cost
15/64 ~= 0.23 us.  vs_baseline = baseline_p50 / our_p50 (>1 is better
than baseline).

Run on the real TPU chip (replicas folded onto one device: XLA executes
the identical collective program; ICI hops are absent, matching how the
driver benches single-chip).  Falls back to CPU when no TPU is present.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax

    from apus_tpu.core.cid import Cid
    from apus_tpu.ops.commit import (CommitControl, build_commit_step,
                                     build_pipelined_commit_step, place_batch)
    from apus_tpu.ops.logplane import host_batch_to_device, make_device_log
    from apus_tpu.ops.mesh import replica_mesh, replica_sharding

    R, S, SB, B = 5, 4096, 4096, 64      # 5 replicas, 16 MB log each, 64-batch
    D = int(os.environ.get("APUS_BENCH_DEPTH", "1024"))
    mesh = replica_mesh(R, devices=jax.devices()[:1])
    sh = replica_sharding(mesh)
    cid = Cid.initial(R)

    # Redis-SET-shaped payloads (the run.sh benchmark shape: redis-benchmark
    # -t set, benchmarks/run.sh:70-80).
    reqs = [b"*3\r\n$3\r\nSET\r\n$16\r\nkey:%012d\r\n$64\r\n%s\r\n"
            % (i, b"x" * 64) for i in range(B)]
    bd, bm, nv = host_batch_to_device(reqs, SB, batch_size=B)
    bdata, bmeta = place_batch(mesh, R, 0, bd, bm)
    sdata, smeta = bdata[None], bmeta[None]     # one resident staged batch

    # -- pipelined steady state (headline) --------------------------------
    pipe = build_pipelined_commit_step(mesh, R, S, SB, B, depth=D,
                                       staged_depth=1)
    devlog = make_device_log(R, S, SB, batch=B, leader=0, term=1, sharding=sh)
    ctrl = CommitControl.from_cid(cid, R, 0, 1, 1)
    devlog, commits, ctrl = pipe(devlog, sdata, smeta, ctrl)    # warmup
    jax.block_until_ready(commits)
    assert int(np.asarray(commits)[-1]) == 1 + D * B, "pipeline did not commit"

    dispatches = 10
    walls_us = []
    for _ in range(dispatches):
        t0 = time.perf_counter_ns()
        devlog, commits, ctrl = pipe(devlog, sdata, smeta, ctrl)
        jax.block_until_ready(commits)
        walls_us.append((time.perf_counter_ns() - t0) / 1e3)
    walls_us.sort()
    wall_p50 = walls_us[len(walls_us) // 2]
    round_p50 = wall_p50 / D
    per_entry_p50 = round_p50 / B
    commits_per_sec = 1e6 / round_p50          # rounds (quorum commits)/sec

    # -- single-dispatch round (for reference; RTT-dominated on tunnel) ---
    step = build_commit_step(mesh, R, S, SB, B, auto_advance=True)
    devlog1 = make_device_log(R, S, SB, batch=B, leader=0, term=1, sharding=sh)
    c1 = CommitControl.from_cid(cid, R, 0, 1, 1)
    cur, _, commit, c1 = step(devlog1, bdata, bmeta, c1)
    jax.block_until_ready(commit)
    lat = []
    for _ in range(20):
        t0 = time.perf_counter_ns()
        cur, _, commit, c1 = step(cur, bdata, bmeta, c1)
        jax.block_until_ready(commit)
        lat.append((time.perf_counter_ns() - t0) / 1e3)
    lat.sort()
    single_p50 = lat[len(lat) // 2]

    baseline_round_us = 15.0             # RDMA commit-round envelope (see doc)
    vs_baseline = baseline_round_us / round_p50

    result = {
        "metric": "commit_round_p50_latency_batch64_5rep_pipelined",
        "value": round(round_p50, 3),
        "unit": "us",
        "vs_baseline": round(vs_baseline, 4),
        "detail": {
            "backend": jax.default_backend(),
            "pipeline_depth": D,
            "dispatch_wall_p50_us": round(wall_p50, 1),
            "single_dispatch_round_p50_us": round(single_p50, 2),
            "per_entry_p50_us": round(per_entry_p50, 4),
            "commits_per_sec": round(commits_per_sec),
            "entries_per_sec": round(commits_per_sec * B),
            "batch": B, "replicas": R, "slot_bytes": SB,
            "baseline_round_us": baseline_round_us,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
